"""Continuous batching: slot-level request interleaving on the fused engine.

The key properties (VERDICT r1 item 3): concurrent requests produce exactly
the tokens they would produce run serially (per-slot offsets, sampler state
and PRNG chains are fully independent), requests genuinely interleave in one
engine, slots are reclaimed and reused, and batched decode beats serial
throughput.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.scheduler import ContinuousBatcher

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(eng)
    ref_gen = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    yield batcher, ref_gen
    batcher.close()


def _run(gen, prompt, **kw):
    return [t for t, _ in gen.generate_step(prompt, **kw)]


def _concurrent(batcher, jobs):
    """Run several generate_step calls in parallel threads, recording each
    token's arrival time."""
    results = [None] * len(jobs)
    times = [None] * len(jobs)

    def worker(i, prompt, kw):
        toks, stamps = [], []
        for t, _ in batcher.generate_step(prompt, **kw):
            toks.append(t)
            stamps.append(time.monotonic())
        results[i] = toks
        times[i] = stamps

    threads = [
        threading.Thread(target=worker, args=(i, p, kw))
        for i, (p, kw) in enumerate(jobs)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=300)
        assert not th.is_alive(), "generation thread hung"
    return results, times


def test_concurrent_greedy_matches_serial(setup):
    batcher, ref_gen = setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=10)),
        ([9, 1, 4, 7], dict(max_tokens=10)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, times = _concurrent(batcher, jobs)
    assert got == refs
    # genuine interleaving: each request produced a token before the other
    # finished (they shared the engine, not took turns with it)
    assert times[0][0] < times[1][-1] and times[1][0] < times[0][-1]


def test_concurrent_seeded_sampling_matches_serial(setup):
    """Per-slot PRNG chains: a seeded stochastic request yields the same
    tokens alone or interleaved with a different request."""
    batcher, ref_gen = setup
    jobs = [
        ([5, 6, 2], dict(temperature=0.9, top_p=0.8, seed=11, max_tokens=8)),
        ([8, 8, 1], dict(temperature=1.3, top_p=0.95, seed=977, max_tokens=8)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == refs


def test_repetition_penalty_context_matches_serial(setup):
    batcher, ref_gen = setup
    kw = dict(repetition_penalty=1.4, repetition_context_size=6, max_tokens=10)
    prompt = [3, 3, 7, 7, 2]
    ref = _run(ref_gen, prompt, **kw)
    got, _ = _concurrent(batcher, [(prompt, kw), ([1, 2], dict(max_tokens=10))])
    assert got[0] == ref


def test_more_requests_than_slots(setup):
    """3 requests on a 2-slot engine: the third waits for a free slot, then
    runs correctly (slot state fully reset between tenants)."""
    batcher, ref_gen = setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=6)),
        ([9, 1, 4, 7], dict(max_tokens=6)),
        ([5, 5, 5], dict(max_tokens=6)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == refs


def test_multichunk_prompt_admission(setup):
    """Prompts longer than one prefill chunk admit via chunked slot prefill
    while the other slot keeps decoding."""
    batcher, ref_gen = setup
    long_prompt = list(range(1, 20))  # chunk=8 -> 8+8+3
    jobs = [
        (long_prompt, dict(max_tokens=6)),
        ([2, 9], dict(max_tokens=12)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == refs


def test_capacity_error(setup):
    batcher, _ = setup
    with pytest.raises(ValueError, match="exceeds KV capacity"):
        list(batcher.generate_step(list(range(30)), max_tokens=200))


def test_throughput_beats_serial(setup):
    """Aggregate decode throughput of 2 interleaved requests must beat the
    same 2 requests run back-to-back through the batcher (the fused step
    advances both slots in S+M-1 ticks instead of 2x S ticks)."""
    batcher, _ = setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=25)),
        ([9, 1, 4], dict(max_tokens=25)),
    ]
    # warmup (compile both programs)
    _concurrent(batcher, [(p, dict(max_tokens=3)) for p, _ in jobs])

    def serial_once():
        t0 = time.monotonic()
        for p, kw in jobs:
            _run(batcher, p, **kw)
        return time.monotonic() - t0

    def concurrent_once():
        t0 = time.monotonic()
        _concurrent(batcher, jobs)
        return time.monotonic() - t0

    # best-of-2 each to shrug off CI noise
    serial = min(serial_once(), serial_once())
    concurrent = min(concurrent_once(), concurrent_once())
    assert concurrent < serial, (
        f"interleaved ({concurrent:.2f}s) not faster than serial ({serial:.2f}s)"
    )


def test_oversized_logit_bias_rejected_on_submit(setup):
    """A >512-entry logit_bias raises on the submitting thread BEFORE the
    scheduler sees it — the scheduler thread must never die on bad input."""
    batcher, _ = setup
    bias = {i: 1.0 for i in range(600)}
    with pytest.raises(ValueError, match="bias width"):
        list(batcher.generate_step([1, 2], logit_bias=bias, max_tokens=2))
    # scheduler still healthy afterwards
    assert _run(batcher, [3, 4], max_tokens=3)


def test_close_unblocks_consumers():
    """close() during in-flight generation ends the stream instead of
    hanging the consumer thread (generator hot-swap path)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    b = ContinuousBatcher(eng)
    got = []

    def worker():
        for t, _ in b.generate_step([3, 1], max_tokens=50):
            got.append(t)
            if len(got) == 3:
                b.close()

    th = threading.Thread(target=worker)
    th.start()
    th.join(timeout=120)
    assert not th.is_alive(), "consumer hung after close()"
    assert len(got) >= 3


def test_multichunk_seeded_admission_deterministic(setup):
    """Regression: decode ticks between a request's prefill chunks split ALL
    PRNG keys and shift ALL repetition windows — slot state must be seeded at
    prefill COMPLETION, or a multi-chunk seeded/penalized request diverges
    from its solo run when admitted next to an active stream."""
    batcher, ref_gen = setup
    long_prompt = list(range(1, 20))  # 3 chunks at prefill_chunk=8
    kw = dict(
        temperature=0.9, top_p=0.85, seed=123,
        repetition_penalty=1.3, repetition_context_size=8, max_tokens=8,
    )
    ref = _run(ref_gen, long_prompt, **kw)
    # busy neighbor decodes while the long prompt admits chunk by chunk
    got, _ = _concurrent(
        batcher, [([7, 7, 2], dict(max_tokens=14)), (long_prompt, kw)]
    )
    assert got[1] == ref


def test_oversized_repetition_context_rejected(setup):
    batcher, _ = setup
    with pytest.raises(ValueError, match="exceeds the scheduler's window"):
        list(
            batcher.generate_step(
                [1, 2], repetition_penalty=1.2, repetition_context_size=100,
                max_tokens=2,
            )
        )


def test_concurrent_logprobs_summaries(setup):
    """want_logprobs through the batcher: TokenLogprobs summaries from the
    decode block, a full lazy row for the first (prefill-sampled) token."""
    import numpy as np

    from mlx_sharding_tpu.generate import TokenLogprobs

    batcher, _ = setup
    out = list(
        batcher.generate_step([3, 1, 4], max_tokens=6, want_logprobs=True)
    )
    assert len(out) == 6
    first_tok, first_lp = out[0]
    assert first_lp is not None and not isinstance(first_lp, TokenLogprobs)
    for tok, lp in out[1:]:
        assert isinstance(lp, TokenLogprobs)
        vals = np.asarray(lp.top_values)
        assert (np.diff(vals) <= 1e-6).all()
        assert int(lp.top_indices[0]) == tok  # greedy -> argmax is chosen
        assert lp.chosen == pytest.approx(float(vals[0]), abs=1e-5)
    # parity with the default path's tokens
    plain = [t for t, _ in batcher.generate_step([3, 1, 4], max_tokens=6)]
    assert [t for t, _ in out] == plain


def test_single_stage_batched_step_parity():
    """pp=1 continuous batching takes the VECTORIZED engine body (one
    vmapped forward for all slots — the aggregate-throughput path on a
    single chip) instead of the tick rotation; streams must still match the
    serial generator exactly, greedy and seeded-sampled, interleaved."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=3, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(eng, decode_block=4)
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    try:
        jobs = [
            ([3, 17, 42], dict(max_tokens=9, seed=1)),
            ([9, 9, 31, 5], dict(max_tokens=7, temperature=0.8, seed=2)),
            ([1, 2], dict(max_tokens=11, temperature=0.5, top_p=0.9, seed=3,
                          repetition_penalty=1.2)),
        ]
        got = _concurrent(batcher, jobs)[0]
        for (prompt, kw), toks in zip(jobs, got):
            assert toks == _run(ref, prompt, **kw), (prompt, kw)
    finally:
        batcher.close()


# ----------------------------------------------------------------- over-commit
def _paged_batcher(pool_pages=8, microbatches=2, **kw):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=microbatches,
        max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=pool_pages, page_size=8,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return ContinuousBatcher(eng, decode_block=3, **kw), ref


@pytest.fixture(scope="module")
def oc_setup():
    """One 8-page pool where each test request's FULL need is 6 pages — two
    can never be co-resident under reserve admission, but over-commit admits
    both on current need and preempts under pressure."""
    batcher, ref = _paged_batcher(pool_pages=8, overcommit=True)
    yield batcher, ref
    batcher.close()


def test_overcommit_requires_paged(setup):
    batcher, _ = setup  # dense engine from the module fixture
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(batcher.engine, overcommit=True)


def test_overcommit_preempt_resume_seeded_exact(oc_setup):
    """A seeded stochastic request that gets preempted and resumed must
    continue its exact PRNG chain and repetition window: its stream matches
    the uninterrupted solo run token-for-token."""
    batcher, ref = oc_setup
    jobs = [
        ([7, 7, 2, 1], dict(max_tokens=40)),  # greedy hog, admitted first
        ([9, 4, 4, 6], dict(temperature=0.9, top_p=0.85, seed=321,
                            repetition_penalty=1.3, repetition_context_size=8,
                            max_tokens=36)),
    ]
    refs = [_run(ref, p, **kw) for p, kw in jobs]
    before = batcher.preemptions
    got, _ = _concurrent(batcher, jobs)
    assert got == refs
    assert batcher.preemptions > before
    # pool accounting intact after the churn: everything back on the free list
    total, in_use, _ = batcher.page_stats()
    assert in_use == 0 and len(batcher._free_pages) == total


# (Heavier over-commit / speculation composition cases — each building its
# own engines — live in tests/test_scheduler_heavy.py, outside the quick
# tier; the representatives here keep the tier's scheduler signal.)


# --------------------------------------------- speculative continuous batching
def _spec_batcher(microbatches=3, spec_k=3, pool_pages=None, draft_seed=7,
                  **kw):
    """Target + draft of the same tiny arch; ``draft_seed`` controls
    agreement (same seed → perfect draft, different → imperfect, so both
    the accept and the reject/correction paths run)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    dparams = model.init_params(jax.random.PRNGKey(draft_seed), jnp.float32)
    mesh = pipeline_mesh(1)
    eng = PipelineEngine(
        model, params, mesh, microbatches=microbatches, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=pool_pages, page_size=8 if pool_pages else None,
    )
    deng = PipelineEngine(
        model, dparams, mesh, microbatches=microbatches, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return (
        ContinuousBatcher(eng, decode_block=4, draft_engine=deng,
                          spec_k=spec_k, **kw),
        ref,
    )


@pytest.fixture(scope="module")
def spec_setup():
    batcher, ref = _spec_batcher()
    yield batcher, ref
    batcher.close()


@pytest.mark.slow  # spec greedy exactness also pinned quick by test_speculative
def test_spec_cb_greedy_token_exact(spec_setup):
    """Speculative continuous batching emits exactly the tokens plain
    (non-speculative) greedy decode would, for every interleaved request —
    whatever the draft proposes only throughput may change, never content."""
    batcher, ref = spec_setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=12)),
        (list(range(1, 20)), dict(max_tokens=9)),  # multi-chunk admission
        ([9, 1, 4, 7], dict(max_tokens=11,
                            repetition_penalty=1.3,
                            repetition_context_size=8)),
    ]
    refs = [_run(ref, p, **kw) for p, kw in jobs]
    r0, a0 = batcher.rounds, batcher.accepted_tokens
    got, times = _concurrent(batcher, jobs)
    assert got == refs
    assert batcher.rounds > r0
    assert batcher.accepted_tokens - a0 >= batcher.rounds - r0  # >= 1/round
    # genuinely interleaved, not serialized
    assert times[0][0] < times[1][-1] and times[1][0] < times[0][-1]


def test_spec_cb_sampled_interleaving_independent(spec_setup):
    """Sampled requests under speculation: per-slot PRNG chains make a
    seeded request's stream identical run solo or interleaved with
    spec-compatible neighbors (both through the speculative path; matching
    NON-speculative streams is not promised — the PRNG is consumed
    differently — and a neighbor that pauses speculation shifts sampled
    chains too, per the scheduler docstring carve-out)."""
    batcher, _ = spec_setup
    jobs = [
        ([5, 6, 2], dict(temperature=0.9, top_p=0.8, seed=11, max_tokens=9)),
        ([8, 8, 1], dict(temperature=1.2, top_p=0.95, seed=97, max_tokens=8)),
        ([2, 4], dict(max_tokens=10)),  # greedy neighbor in the same rounds
    ]
    solo = [_run(batcher, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == solo


def test_spec_cb_logprobs_falls_back_unspeculated(spec_setup):
    """A want_logprobs request pauses speculation (the verify computes no
    summaries): tokens still exact, summaries well-formed, rounds frozen."""
    from mlx_sharding_tpu.generate import TokenLogprobs

    batcher, ref = spec_setup
    r0 = batcher.rounds
    out = list(batcher.generate_step([3, 1, 4], max_tokens=6,
                                     want_logprobs=True))
    assert [t for t, _ in out] == _run(ref, [3, 1, 4], max_tokens=6)
    assert batcher.rounds == r0
    assert all(isinstance(lp, TokenLogprobs) for _, lp in out[1:])


def test_spec_cb_guards():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng2 = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    deng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    with pytest.raises(ValueError, match="pp=1"):
        ContinuousBatcher(eng2, draft_engine=deng)


# ---------------------------------------------------------------- prefix cache
def _paged_cached_batcher(pool_pages=24, microbatches=2, **kw):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=microbatches,
        max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=pool_pages, page_size=8,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return ContinuousBatcher(eng, decode_block=3, prefix_cache=True, **kw), ref


def test_prefix_cache_requires_paged(setup):
    batcher, _ = setup  # dense engine from the module fixture
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(batcher.engine, prefix_cache=True)


def test_prefix_cache_hit_token_exact():
    """A repeated prompt reuses its full prompt pages (minus the final
    token's page) and still matches the serial generator token-for-token."""
    batcher, ref = _paged_cached_batcher()
    try:
        prompt = [((7 * i) % 251) + 1 for i in range(20)]  # 2 full pages + 4
        want = _run(ref, prompt, max_tokens=8)
        first = _run(batcher, prompt, max_tokens=8)
        assert first == want
        q0, h0, reused0, _, cached0 = batcher.prefix_stats()
        assert h0 == 0 and cached0 >= 2  # cold query registered its pages
        second = _run(batcher, prompt, max_tokens=8)
        assert second == want
        q1, h1, reused1, _, _ = batcher.prefix_stats()
        assert (q1, h1) == (q0 + 1, 1)
        assert reused1 == 16  # two 8-token pages; the tail re-prefills
    finally:
        batcher.close()


def test_prefix_cache_interleaved_token_exact():
    """Two concurrent requests sharing a 16-token system prefix with
    different suffixes: token-exact vs the serial path, with a prefix hit
    recorded for whichever admits second."""
    batcher, ref = _paged_cached_batcher()
    try:
        system = [((11 * i) % 250) + 1 for i in range(16)]
        jobs = [
            (system + [61, 62, 63], dict(max_tokens=8, seed=5,
                                         temperature=0.7)),
            (system + [71, 72], dict(max_tokens=10)),
        ]
        # warm the cache with a third request sharing the prefix, so BOTH
        # concurrent requests hit regardless of admission order
        warm = _run(batcher, system + [99], max_tokens=2)
        assert len(warm) == 2
        got, _ = _concurrent(batcher, jobs)
        for (prompt, kw), toks in zip(jobs, got):
            assert toks == _run(ref, prompt, **kw), (prompt, kw)
        _, hits, reused, _, _ = batcher.prefix_stats()
        assert hits >= 2
        assert reused >= 2 * 16
    finally:
        batcher.close()


def test_prefix_cache_eviction_and_no_leaks():
    """Distinct prompts big enough to overflow the pool force LRU eviction
    of cached pages; accounting stays exact: after everything finishes,
    free + cached == pool."""
    batcher, ref = _paged_cached_batcher(pool_pages=8)
    try:
        prompts = [
            [((13 * i + s) % 250) + 1 for i in range(17)] for s in range(4)
        ]
        for p in prompts:
            assert _run(batcher, p, max_tokens=4) == _run(ref, p, max_tokens=4)
        _, _, _, evictions, cached = batcher.prefix_stats()
        assert evictions > 0
        total, in_use, _ = batcher.page_stats()
        assert in_use == cached  # only cache entries hold pages now
        assert len(batcher._free_pages) + cached == total
        # and a cached prompt still hits after the shuffle
        hits_before = batcher.prefix_stats()[1]
        assert _run(batcher, prompts[-1], max_tokens=4) == _run(
            ref, prompts[-1], max_tokens=4
        )
        assert batcher.prefix_stats()[1] == hits_before + 1
    finally:
        batcher.close()


@pytest.mark.slow  # eviction-pressure sweep — the other prefix tests stay quick
def test_prefix_cache_own_chain_not_evicted_under_pressure():
    """Regression: when the only evictable cached pages ARE the incoming
    request's prefix chain, the request must wait for capacity, not evict
    its own chain out from under itself (which popped the page's refcount
    entry and KeyError'd the scheduler thread, failing every request)."""
    batcher, ref = _paged_cached_batcher(pool_pages=6)
    try:
        shared_prompt = [((7 * i) % 251) + 1 for i in range(17)]  # 2 cached pages
        assert _run(batcher, shared_prompt, max_tokens=4) == _run(
            ref, shared_prompt, max_tokens=4
        )
        assert batcher.prefix_stats()[4] == 2  # two pages cached

        # occupy 3 of the remaining pages with a long-running request, so
        # free=1 and the only other pages are the cached chain itself
        hog_prompt = [((5 * i) % 250) + 2 for i in range(9)]
        hog_done = threading.Event()
        hog_out = []

        def hog():
            hog_out.extend(_run(batcher, hog_prompt, max_tokens=20))
            hog_done.set()

        th = threading.Thread(target=hog)
        th.start()
        time.sleep(0.5)  # let the hog admit
        # chain=2 shared, needs 2 fresh pages, free=1, nothing else
        # evictable -> must WAIT (crash = _fail_all = exception here)
        toks = _run(batcher, shared_prompt, max_tokens=15)
        th.join(timeout=120)
        assert hog_done.is_set()
        assert hog_out == _run(ref, hog_prompt, max_tokens=20)
        assert toks == _run(ref, shared_prompt, max_tokens=15)
        assert batcher.prefix_stats()[1] >= 1  # the chain WAS reused
    finally:
        batcher.close()


# ------------------------------------------------ ragged paged decode (ISSUE 1)
def _ragged_batcher(paged_attention, pool_pages=10, **kw):
    """pp=1 paged engine: the only wiring the ragged in-place attention path
    supports (ops/paged_attention.py via the vectorized decode body)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=3, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=pool_pages, page_size=8, paged_attention=paged_attention,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return ContinuousBatcher(eng, decode_block=3, **kw), ref


def test_ragged_mixed_length_cb_matches_serial():
    """Mixed-length concurrent run on the ragged path (pool attended in
    place, per-slot lengths masked in-kernel): every stream token-exact vs
    its solo serial run, and the KV accounting reports the ragged path."""
    batcher, ref = _ragged_batcher("ragged")
    try:
        assert batcher.engine.paged_attention == "ragged"
        rng = np.random.default_rng(3)
        jobs = []
        for i, plen in enumerate([2, 8, 11, 19]):  # straddle page boundaries
            prompt = [int(t) for t in rng.integers(1, 256, size=plen)]
            jobs.append((prompt, dict(max_tokens=5 + 2 * i, seed=i,
                                      temperature=0.6)))
        want = [_run(ref, p, **kw) for p, kw in jobs]
        got, _ = _concurrent(batcher, jobs)
        assert got == want
        path, last_tick, total = batcher.kv_read_stats()
        assert path == "ragged" and total > 0
    finally:
        batcher.close()


def test_kv_read_accounting_ragged_below_gather():
    """Same short run on both paths: the ragged analytic KV-bytes-read must
    come in strictly below gather's (gather always reads every slot's full
    slot_pages regardless of true length)."""
    totals = {}
    for path in ("ragged", "gather"):
        batcher, _ = _ragged_batcher(path)
        try:
            _run(batcher, [5, 3], max_tokens=8)
            totals[path] = batcher.kv_read_stats()[2]
        finally:
            batcher.close()
    assert 0 < totals["ragged"] < totals["gather"]


def test_overcommit_pool_exhaustion_errors_not_wedges():
    """If the pool truly cannot cover a lone request's next decode block
    (only reachable through accounting drift), the request must FAIL with a
    loud error, not wedge against the scratch page emitting garbage. Drift
    is simulated by vanishing the free list mid-decode."""
    batcher, _ = _paged_batcher(pool_pages=4, overcommit=True)
    try:
        gen = batcher.generate_step([5, 9], max_tokens=24)  # 4-page full need
        next(gen)  # prefill done, decode under way
        batcher._free_pages = []  # simulate the drift: pool gone
        with pytest.raises(RuntimeError, match="pool exhausted"):
            for _ in gen:
                pass
    finally:
        batcher.close()


@pytest.fixture(scope="module")
def spec_perfect():
    """draft == target: every proposal verifies, so acceptance statistics
    become deterministic signal instead of noise."""
    batcher, ref = _spec_batcher(draft_seed=0)
    yield batcher, ref
    batcher.close()


def test_spec_accepted_counts_only_emitted(spec_perfect):
    """accepted_tokens is throughput telemetry: a final round whose accepted
    run overshoots the request's remaining budget must count only what was
    emitted, not the whole run."""
    batcher, ref = spec_perfect
    a0 = batcher.accepted_tokens
    out = _run(batcher, [4, 2], max_tokens=2)  # 1 prefill + 1 spec token
    assert out == _run(ref, [4, 2], max_tokens=2)
    assert batcher.accepted_tokens - a0 == max(0, len(out) - 1)


def test_spec_draft_replay_after_fallback_keeps_acceptance(spec_perfect):
    """A want_logprobs neighbor forces non-speculative ticks for EVERY live
    slot; the draft must be replayed through those emitted tokens or its KV
    desyncs and acceptance collapses once speculation resumes. With a
    perfect draft, post-fallback rounds must keep accepting multiple tokens
    per round."""
    batcher, ref = spec_perfect
    f0, p0 = batcher.fallback_ticks, batcher.replayed_tokens
    r0, a0 = batcher.rounds, batcher.accepted_tokens
    jobs = [
        ([3, 1, 4], dict(max_tokens=8, want_logprobs=True)),
        ([5, 2, 6], dict(max_tokens=24)),  # outlives the logprobs neighbor
    ]
    got, _ = _concurrent(batcher, jobs)
    assert got[0] == _run(ref, [3, 1, 4], max_tokens=8)
    assert got[1] == _run(ref, [5, 2, 6], max_tokens=24)
    assert batcher.fallback_ticks > f0  # the fallback ticks really happened
    assert batcher.replayed_tokens > p0  # and the draft replayed through them
    rounds = batcher.rounds - r0
    accepted = batcher.accepted_tokens - a0
    assert rounds > 0
    # a desynced draft degenerates to ~1 accepted/round; the replayed one
    # keeps the perfect draft's multi-token acceptance
    assert accepted >= 2 * rounds


# ------------------------------------------- async tick pipelining (ISSUE 4)
# The module fixtures above already run the async path (async_sched defaults
# to "auto" = on for plain single-host decode), so every stream-vs-serial
# assertion in this file doubles as async-correctness coverage — including
# overcommit preemption (oc_setup) and pool exhaustion. The tests below pin
# the explicit sync-vs-async contract: BIT-IDENTICAL token streams, clean
# one-tick-lag handling, and clean shedding when the in-flight block dies.


def test_async_sched_validation(setup, spec_setup):
    batcher, _ = setup
    spec, _ = spec_setup
    assert batcher._async  # auto -> on for plain single-host decode
    assert not spec._async  # auto -> off with a draft engine attached
    with pytest.raises(ValueError, match="async_sched"):
        ContinuousBatcher(batcher.engine, async_sched="sometimes")
    with pytest.raises(ValueError, match="draft"):
        ContinuousBatcher(
            spec.engine, draft_engine=spec.draft, async_sched="on"
        )
    off = ContinuousBatcher(batcher.engine, async_sched="off")
    try:
        assert not off._async
        assert off.tick_timing_stats()["path"] == "sync"
    finally:
        off.close()
    assert batcher.tick_timing_stats()["path"] == "async"


def test_async_matches_sync_token_exact_matrix():
    """The core contract: the double-buffered pipeline emits BIT-IDENTICAL
    streams to the classic loop across the request matrix — greedy, seeded
    sampling, multi-chunk admission, repetition penalty, and max_tokens
    boundaries (1-token streams and streams that run to their budget)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)

    def make(mode):
        eng = PipelineEngine(
            model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        return ContinuousBatcher(eng, async_sched=mode)

    jobs = [
        ([3, 17, 42], dict(max_tokens=10)),  # greedy
        ([5, 6, 2], dict(temperature=0.9, top_p=0.8, seed=11,
                         max_tokens=8)),  # seeded sampled
        (list(range(1, 20)), dict(max_tokens=6)),  # multi-chunk admission
        ([9, 1, 4, 7], dict(max_tokens=1)),  # max_tokens boundary: one token
        ([3, 3, 7, 7, 2], dict(repetition_penalty=1.4,
                               repetition_context_size=6, max_tokens=12)),
    ]
    streams = {}
    for mode in ("off", "on"):
        batcher = make(mode)
        try:
            got, _ = _concurrent(batcher, jobs[:2])
            got += _concurrent(batcher, jobs[2:4])[0]
            got.append(_run(batcher, jobs[4][0], **jobs[4][1]))
            streams[mode] = got
        finally:
            batcher.close()
    assert streams["on"] == streams["off"]
    assert all(len(s) for s in streams["on"])


def test_async_mid_stream_cancellation_sheds_lookahead():
    """A client dropping its stream mid-generation under the async loop: the
    one-tick control lag means a lookahead block for the dead slot may still
    complete on device — its tokens must be dropped host-side, its pages
    returned, and the surviving stream must stay token-exact. (Server-side
    stop sequences cancel streams through this same path.)"""
    batcher, ref = _paged_batcher(pool_pages=8)
    try:
        assert batcher._async
        survivor_kw = dict(max_tokens=16)
        want = _run(ref, [9, 4, 4, 6], **survivor_kw)
        got = []
        cancelled_tokens = []

        def cancel_worker():
            gen = batcher.generate_step([7, 7, 2, 1], max_tokens=30)
            for t, _ in gen:
                cancelled_tokens.append(t)
                if len(cancelled_tokens) == 3:
                    gen.close()  # client walked away mid-stream
                    return

        def survivor_worker():
            got.extend(_run(batcher, [9, 4, 4, 6], **survivor_kw))

        threads = [
            threading.Thread(target=cancel_worker),
            threading.Thread(target=survivor_worker),
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
            assert not th.is_alive(), "generation thread hung"
        assert got == want
        assert len(cancelled_tokens) == 3
        # a follow-up request forces the loop through quiesce + admission;
        # after it the cancelled slot's pages must all be home
        assert _run(batcher, [1, 2], max_tokens=3) == _run(
            ref, [1, 2], max_tokens=3
        )
        total, in_use, _ = batcher.page_stats()
        assert in_use == 0 and len(batcher._free_pages) == total
        assert all(r is None for r in batcher._slots)
    finally:
        batcher.close()


def test_async_harvest_fault_sheds_cleanly():
    """Kill the in-flight block at the harvest boundary (the new
    scheduler.harvest fault site): every consumer gets the error instead of
    hanging, no slot stays wedged, every page returns to the pool, and the
    batcher serves the next request normally."""
    from mlx_sharding_tpu.testing import faults

    batcher, ref = _paged_batcher(pool_pages=8)
    try:
        assert batcher._async
        f = faults.arm("scheduler.harvest", exc=RuntimeError("harvest kill"),
                       after=2, times=1)
        errors = []

        def worker(prompt):
            try:
                _run(batcher, prompt, max_tokens=24)
            except RuntimeError as e:
                errors.append(str(e))

        threads = [
            threading.Thread(target=worker, args=(p,))
            for p in ([7, 7, 2, 1], [9, 4, 4, 6])
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
            assert not th.is_alive(), "consumer hung after harvest fault"
        assert f.fired == 1
        assert len(errors) == 2 and all("harvest kill" in e for e in errors)
        # clean shed: no wedged slots, the whole pool back on the free list.
        # _fail_all surfaces the error to consumers BEFORE its pool reset,
        # so give the scheduler thread a beat to finish the reset.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            total, in_use, _ = batcher.page_stats()
            if in_use == 0 and len(batcher._free_pages) == total:
                break
            time.sleep(0.01)
        assert all(r is None for r in batcher._slots)
        total, in_use, _ = batcher.page_stats()
        assert in_use == 0 and len(batcher._free_pages) == total
        # and the scheduler thread survived to serve the next request
        assert _run(batcher, [3, 4], max_tokens=4) == _run(
            ref, [3, 4], max_tokens=4
        )
    finally:
        faults.disarm()
        batcher.close()


@pytest.mark.slow  # engine-pair sweep; the quick tier covers async prefix
def test_async_prefix_cache_hits_match_sync():
    """Prefix-cache hits through both run loops: identical streams and
    identical hit/reuse accounting (admission prefill quiesces the in-flight
    block, so a hit can never race the lookahead)."""
    stats = {}
    streams = {}
    prompt = [((7 * i) % 251) + 1 for i in range(20)]
    for mode in ("off", "on"):
        batcher, ref = _paged_cached_batcher(async_sched=mode)
        try:
            first = _run(batcher, prompt, max_tokens=8)
            second = _run(batcher, prompt, max_tokens=8)
            assert first == second == _run(ref, prompt, max_tokens=8)
            streams[mode] = (first, second)
            q, h, reused, _, cached = batcher.prefix_stats()
            stats[mode] = (q, h, reused, cached)
        finally:
            batcher.close()
    assert streams["on"] == streams["off"]
    assert stats["on"] == stats["off"]
    assert stats["on"][1] == 1  # the repeat really hit


@pytest.mark.slow  # engine-pair sweep; oc_setup covers async+overcommit
def test_async_overcommit_preemption_matches_sync():
    """Preemption under over-commit through both run loops: identical
    streams (quiesce-before-preempt keeps token accounting exact under the
    one-tick lag) and a fully-free pool afterwards."""
    streams = {}
    jobs = [
        ([7, 7, 2, 1], dict(max_tokens=40)),
        ([9, 4, 4, 6], dict(temperature=0.9, top_p=0.85, seed=321,
                            repetition_penalty=1.3, repetition_context_size=8,
                            max_tokens=36)),
    ]
    for mode in ("off", "on"):
        batcher, _ = _paged_batcher(pool_pages=8, overcommit=True,
                                    async_sched=mode)
        try:
            before = batcher.preemptions
            got, _ = _concurrent(batcher, jobs)
            assert batcher.preemptions > before
            total, in_use, _ = batcher.page_stats()
            assert in_use == 0 and len(batcher._free_pages) == total
            streams[mode] = got
        finally:
            batcher.close()
    assert streams["on"] == streams["off"]


def test_async_tick_timing_stats_populated(setup):
    """The per-tick host / device-blocked split feeding /metrics and the
    bench's async_tick_overlap phase: ticks counted, averages finite."""
    batcher, _ = setup
    _run(batcher, [2, 9, 5], max_tokens=6)
    t = batcher.tick_timing_stats()
    assert t["path"] == "async"
    assert t["ticks"] > 0
    assert t["device_blocked_ms_avg"] >= 0.0
    assert t["host_ms_avg"] >= 0.0
    assert t["device_blocked_ms_last"] >= 0.0
