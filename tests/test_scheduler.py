"""Continuous batching: slot-level request interleaving on the fused engine.

The key properties (VERDICT r1 item 3): concurrent requests produce exactly
the tokens they would produce run serially (per-slot offsets, sampler state
and PRNG chains are fully independent), requests genuinely interleave in one
engine, slots are reclaimed and reused, and batched decode beats serial
throughput.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.scheduler import ContinuousBatcher

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(eng)
    ref_gen = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    yield batcher, ref_gen
    batcher.close()


def _run(gen, prompt, **kw):
    return [t for t, _ in gen.generate_step(prompt, **kw)]


def _concurrent(batcher, jobs):
    """Run several generate_step calls in parallel threads, recording each
    token's arrival time."""
    results = [None] * len(jobs)
    times = [None] * len(jobs)

    def worker(i, prompt, kw):
        toks, stamps = [], []
        for t, _ in batcher.generate_step(prompt, **kw):
            toks.append(t)
            stamps.append(time.monotonic())
        results[i] = toks
        times[i] = stamps

    threads = [
        threading.Thread(target=worker, args=(i, p, kw))
        for i, (p, kw) in enumerate(jobs)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=300)
        assert not th.is_alive(), "generation thread hung"
    return results, times


def test_concurrent_greedy_matches_serial(setup):
    batcher, ref_gen = setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=10)),
        ([9, 1, 4, 7], dict(max_tokens=10)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, times = _concurrent(batcher, jobs)
    assert got == refs
    # genuine interleaving: each request produced a token before the other
    # finished (they shared the engine, not took turns with it)
    assert times[0][0] < times[1][-1] and times[1][0] < times[0][-1]


def test_concurrent_seeded_sampling_matches_serial(setup):
    """Per-slot PRNG chains: a seeded stochastic request yields the same
    tokens alone or interleaved with a different request."""
    batcher, ref_gen = setup
    jobs = [
        ([5, 6, 2], dict(temperature=0.9, top_p=0.8, seed=11, max_tokens=8)),
        ([8, 8, 1], dict(temperature=1.3, top_p=0.95, seed=977, max_tokens=8)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == refs


def test_repetition_penalty_context_matches_serial(setup):
    batcher, ref_gen = setup
    kw = dict(repetition_penalty=1.4, repetition_context_size=6, max_tokens=10)
    prompt = [3, 3, 7, 7, 2]
    ref = _run(ref_gen, prompt, **kw)
    got, _ = _concurrent(batcher, [(prompt, kw), ([1, 2], dict(max_tokens=10))])
    assert got[0] == ref


def test_more_requests_than_slots(setup):
    """3 requests on a 2-slot engine: the third waits for a free slot, then
    runs correctly (slot state fully reset between tenants)."""
    batcher, ref_gen = setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=6)),
        ([9, 1, 4, 7], dict(max_tokens=6)),
        ([5, 5, 5], dict(max_tokens=6)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == refs


def test_multichunk_prompt_admission(setup):
    """Prompts longer than one prefill chunk admit via chunked slot prefill
    while the other slot keeps decoding."""
    batcher, ref_gen = setup
    long_prompt = list(range(1, 20))  # chunk=8 -> 8+8+3
    jobs = [
        (long_prompt, dict(max_tokens=6)),
        ([2, 9], dict(max_tokens=12)),
    ]
    refs = [_run(ref_gen, p, **kw) for p, kw in jobs]
    got, _ = _concurrent(batcher, jobs)
    assert got == refs


def test_capacity_error(setup):
    batcher, _ = setup
    with pytest.raises(ValueError, match="exceeds KV capacity"):
        list(batcher.generate_step(list(range(30)), max_tokens=200))


def test_throughput_beats_serial(setup):
    """Aggregate decode throughput of 2 interleaved requests must beat the
    same 2 requests run back-to-back through the batcher (the fused step
    advances both slots in S+M-1 ticks instead of 2x S ticks)."""
    batcher, _ = setup
    jobs = [
        ([3, 17, 42], dict(max_tokens=25)),
        ([9, 1, 4], dict(max_tokens=25)),
    ]
    # warmup (compile both programs)
    _concurrent(batcher, [(p, dict(max_tokens=3)) for p, _ in jobs])

    def serial_once():
        t0 = time.monotonic()
        for p, kw in jobs:
            _run(batcher, p, **kw)
        return time.monotonic() - t0

    def concurrent_once():
        t0 = time.monotonic()
        _concurrent(batcher, jobs)
        return time.monotonic() - t0

    # best-of-2 each to shrug off CI noise
    serial = min(serial_once(), serial_once())
    concurrent = min(concurrent_once(), concurrent_once())
    assert concurrent < serial, (
        f"interleaved ({concurrent:.2f}s) not faster than serial ({serial:.2f}s)"
    )


def test_oversized_logit_bias_rejected_on_submit(setup):
    """A >512-entry logit_bias raises on the submitting thread BEFORE the
    scheduler sees it — the scheduler thread must never die on bad input."""
    batcher, _ = setup
    bias = {i: 1.0 for i in range(600)}
    with pytest.raises(ValueError, match="bias width"):
        list(batcher.generate_step([1, 2], logit_bias=bias, max_tokens=2))
    # scheduler still healthy afterwards
    assert _run(batcher, [3, 4], max_tokens=3)


def test_close_unblocks_consumers():
    """close() during in-flight generation ends the stream instead of
    hanging the consumer thread (generator hot-swap path)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    b = ContinuousBatcher(eng)
    got = []

    def worker():
        for t, _ in b.generate_step([3, 1], max_tokens=50):
            got.append(t)
            if len(got) == 3:
                b.close()

    th = threading.Thread(target=worker)
    th.start()
    th.join(timeout=120)
    assert not th.is_alive(), "consumer hung after close()"
    assert len(got) >= 3


def test_multichunk_seeded_admission_deterministic(setup):
    """Regression: decode ticks between a request's prefill chunks split ALL
    PRNG keys and shift ALL repetition windows — slot state must be seeded at
    prefill COMPLETION, or a multi-chunk seeded/penalized request diverges
    from its solo run when admitted next to an active stream."""
    batcher, ref_gen = setup
    long_prompt = list(range(1, 20))  # 3 chunks at prefill_chunk=8
    kw = dict(
        temperature=0.9, top_p=0.85, seed=123,
        repetition_penalty=1.3, repetition_context_size=8, max_tokens=8,
    )
    ref = _run(ref_gen, long_prompt, **kw)
    # busy neighbor decodes while the long prompt admits chunk by chunk
    got, _ = _concurrent(
        batcher, [([7, 7, 2], dict(max_tokens=14)), (long_prompt, kw)]
    )
    assert got[1] == ref


def test_oversized_repetition_context_rejected(setup):
    batcher, _ = setup
    with pytest.raises(ValueError, match="exceeds the scheduler's window"):
        list(
            batcher.generate_step(
                [1, 2], repetition_penalty=1.2, repetition_context_size=100,
                max_tokens=2,
            )
        )


def test_concurrent_logprobs_summaries(setup):
    """want_logprobs through the batcher: TokenLogprobs summaries from the
    decode block, a full lazy row for the first (prefill-sampled) token."""
    import numpy as np

    from mlx_sharding_tpu.generate import TokenLogprobs

    batcher, _ = setup
    out = list(
        batcher.generate_step([3, 1, 4], max_tokens=6, want_logprobs=True)
    )
    assert len(out) == 6
    first_tok, first_lp = out[0]
    assert first_lp is not None and not isinstance(first_lp, TokenLogprobs)
    for tok, lp in out[1:]:
        assert isinstance(lp, TokenLogprobs)
        vals = np.asarray(lp.top_values)
        assert (np.diff(vals) <= 1e-6).all()
        assert int(lp.top_indices[0]) == tok  # greedy -> argmax is chosen
        assert lp.chosen == pytest.approx(float(vals[0]), abs=1e-5)
    # parity with the default path's tokens
    plain = [t for t, _ in batcher.generate_step([3, 1, 4], max_tokens=6)]
    assert [t for t, _ in out] == plain


def test_single_stage_batched_step_parity():
    """pp=1 continuous batching takes the VECTORIZED engine body (one
    vmapped forward for all slots — the aggregate-throughput path on a
    single chip) instead of the tick rotation; streams must still match the
    serial generator exactly, greedy and seeded-sampled, interleaved."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=3, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(eng, decode_block=4)
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    try:
        jobs = [
            ([3, 17, 42], dict(max_tokens=9, seed=1)),
            ([9, 9, 31, 5], dict(max_tokens=7, temperature=0.8, seed=2)),
            ([1, 2], dict(max_tokens=11, temperature=0.5, top_p=0.9, seed=3,
                          repetition_penalty=1.2)),
        ]
        got = _concurrent(batcher, jobs)[0]
        for (prompt, kw), toks in zip(jobs, got):
            assert toks == _run(ref, prompt, **kw), (prompt, kw)
    finally:
        batcher.close()
