"""Pod-scale multihost fleet (pod.py): cross-host weight store gossip,
disagg handoff over the pod fabric, and the pod autoscaler.

Parity contract: every stream a client sees through a pod-attached
coordinator — including streams whose decode leg ran on a REMOTE host —
is bit-identical to the same request served by one monolithic batcher.
Every ``PodHandoffFallback`` kind (injected fault, unreachable remote,
serialization failure, transfer failure, remote pool error, and the
relay timeout that drains a dead host) must land back on the origin's
local plan, counted by kind, never a dropped stream.

The quick tier runs everything over the in-process :class:`LoopbackHub`;
the slow tier spawns two real processes over gloo collectives and
asserts the module's own acceptance demo (``python -m
mlx_sharding_tpu.pod``) reports ok."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.disagg import DisaggCoordinator
from mlx_sharding_tpu.kv_transfer import BlockIntegrityError, KVPageBlock
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.pod import (
    LoopbackHub,
    PodAutoscaler,
    PodFleet,
    PodHandoff,
    PodHandoffFallback,
    PodWeightRegistry,
)
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.resilience import ResumeState
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.utils.observability import ServingMetrics
from mlx_sharding_tpu.weights import WeightKey, WeightStore, key_digest

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)

# greedy and seeded-stochastic: the remote decode host must reproduce
# both bit-for-bit (the kw whitelist carries the sampler config)
JOBS = [
    ([3, 17, 42], dict(max_tokens=24)),
    ([9, 4, 4, 6], dict(temperature=0.9, top_p=0.85, seed=321,
                        repetition_penalty=1.3, repetition_context_size=8,
                        max_tokens=20)),
]


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _mk_batcher(tiny_model, dev_idx):
    model, params = tiny_model
    devices = jax.devices()
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[dev_idx:dev_idx + 1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=10, page_size=8,
    )
    return ContinuousBatcher(eng, decode_block=3)


@pytest.fixture(scope="module")
def engines(tiny_model):
    """Host 0's coordinator (prefill + decode pools), host 1's decode
    batcher, and the monolithic parity reference — shared across the pod
    tests; each test builds its own fabric around them."""
    co = DisaggCoordinator(
        ReplicaSet([_mk_batcher(tiny_model, 0)], role="prefill"),
        ReplicaSet([_mk_batcher(tiny_model, 1)], role="decode"),
    )
    b1 = _mk_batcher(tiny_model, 2)
    mono = _mk_batcher(tiny_model, 3)
    refs = [[t for t, _ in mono.generate_step(p, **kw)] for p, kw in JOBS]
    yield SimpleNamespace(co=co, b1=b1, refs=refs)
    co.close()
    b1.close()
    mono.close()


@pytest.fixture
def pod(engines):
    """A fresh two-host loopback pod around the shared engines: host 0
    serves the coordinator (its decode pool priced as saturated so every
    handoff prefers the remote), host 1 serves the plain batcher."""
    hub = LoopbackHub()
    f0 = PodFleet(0, hub.register(0), engines.co)
    f1 = PodFleet(1, hub.register(1), engines.b1)
    f0.tick()
    f1.tick()
    f0.handoff.local_pressure = lambda: 1.0
    yield SimpleNamespace(hub=hub, f0=f0, f1=f1, co=engines.co,
                          refs=engines.refs)
    # the shared engines outlive this pod membership (module fixture)
    f0.close(close_local=False)
    f1.close(close_local=False)
    engines.co.pod = None  # detach so later fixtures start clean


# --------------------------------------------------------------- wire format


def _mk_block():
    rng = np.random.default_rng(0)
    k = rng.standard_normal((2, 1, 3, 8, 4)).astype(np.float32)
    blk = KVPageBlock(
        k_pages=k, v_pages=k + 1.0, n_tokens=20, page_size=8,
        prompt=np.array([3, 17, 42], np.int32), history=[5, 6], produced=2,
        last_tok=6, resume_keys=None, resume_recent=None,
    )
    return blk.to_host()


def test_block_wire_roundtrip_bit_exact():
    blk = _mk_block()
    data = blk.to_bytes()
    back = KVPageBlock.from_bytes(data)
    np.testing.assert_array_equal(np.asarray(back.k_pages),
                                  np.asarray(blk.k_pages))
    np.testing.assert_array_equal(np.asarray(back.v_pages),
                                  np.asarray(blk.v_pages))
    assert back.n_tokens == blk.n_tokens
    assert back.history == blk.history
    assert back.last_tok == blk.last_tok
    assert back.checksum == blk.checksum


def test_block_wire_corruption_detected():
    data = _mk_block().to_bytes()
    with pytest.raises(BlockIntegrityError):
        KVPageBlock.from_bytes(data[: len(data) // 2])
    mid = len(data) // 2
    flipped = data[:mid] + bytes([data[mid] ^ 0xFF]) + data[mid + 1:]
    with pytest.raises(BlockIntegrityError):
        KVPageBlock.from_bytes(flipped)


# ------------------------------------------------------------ weight gossip


def test_registry_build_once_and_pod_view():
    key = WeightKey(checkpoint="ck", stage_bounds=(("auto", 1),),
                    dtype="float32", quant="none", placement="h0")
    store = WeightStore()
    builds = []

    def build():
        builds.append(1)
        return object()

    a = store.acquire(key, build)
    b = store.acquire(key, build)
    assert len(builds) == 1  # one packed tree, two local refs
    reg = PodWeightRegistry(store=store)
    info = reg.local_info()
    assert info["trees"] == 1 and info["refs"] == 2
    assert key_digest(key) in info["digests"]

    # the pod view aggregates gossiped peers into the {host=} source
    view = reg.pod_view({1: {"info": {"weights": {"trees": 1, "refs": 3,
                                                  "bytes": 17}}},
                         2: {"info": {}}})
    assert view == {1: {"trees": 1, "refs": 3, "bytes": 17}}

    # teardown broadcast maps a gossiped digest back onto the local key
    torn = []
    reg.set_teardown_handler(torn.append)
    assert reg.handle_teardown(key_digest(key)) == key
    assert torn == [key]
    assert reg.handle_teardown("ffffffffffffffff") is None
    b.release()
    a.release()


def test_registry_teardown_broadcast_over_fabric():
    hub = LoopbackHub()
    t0, t1 = hub.register(0), hub.register(1)
    key = WeightKey(checkpoint="ck", stage_bounds=(("auto", 1),),
                    dtype="float32", quant="none", placement="h1")
    s1 = WeightStore()
    lease = s1.acquire(key, object)
    r1 = PodWeightRegistry(store=s1)
    torn = []
    r1.set_teardown_handler(torn.append)
    t1.set_handler(
        lambda src, kind, payload: r1.handle_teardown(payload.decode()))
    t1.publish({})
    PodWeightRegistry(store=WeightStore()).request_teardown(
        t0, key_digest(key))
    assert torn == [key]
    lease.release()


# ----------------------------------------------------- cross-host handoff


def test_cross_host_handoff_parity(pod):
    for (prompt, kw), ref in zip(JOBS, pod.refs):
        got = [t for t, _ in pod.co.generate_step(prompt, **kw)]
        assert got == ref
    h = pod.f0.handoff.stats()
    assert h["shipped"] == len(JOBS)
    assert h["bytes_shipped"] > 0
    assert h["relayed_tokens"] > 0
    assert h["fallbacks"] == {}
    assert h["ms_p50"] is not None
    assert pod.f1.handoff.stats()["received"] == len(JOBS)


def test_pick_remote_tie_serves_locally(pod):
    # an equally-loaded remote never wins: the wire is not free
    pod.f0.handoff.local_pressure = lambda: 0.0
    assert pod.f0.handoff.pick_remote() is None
    pod.f0.handoff.local_pressure = lambda: 1.0
    assert pod.f0.handoff.pick_remote() == 1
    assert pod.f0.handoff.stats()["fallbacks"] == {}


def test_fallback_remote_unavailable():
    hub = LoopbackHub()
    h = PodHandoff(0, hub.register(0), local_pressure=lambda: 1.0)
    state = ResumeState(prompt=np.array([1, 2], np.int32), history=[],
                        produced=0)
    with pytest.raises(PodHandoffFallback) as exc:
        next(h.serve_remote(state, {}))
    assert exc.value.kind == "remote_unavailable"
    assert exc.value.keep_block
    assert h.stats()["fallbacks"] == {"remote_unavailable": 1}


def test_fallback_injected_handoff_fault(pod):
    faults.arm("pod.handoff", exc=faults.FaultError, times=1)
    got = [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    assert got == pod.refs[0]
    h = pod.f0.handoff.stats()
    assert h["fallbacks"] == {"handoff_fault": 1}
    assert h["shipped"] == 0  # the fault fires before any wire work


def test_fallback_serialize_error(pod, monkeypatch):
    def boom(self):
        raise RuntimeError("unserializable")

    monkeypatch.setattr(KVPageBlock, "to_bytes", boom)
    got = [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    assert got == pod.refs[0]  # serve-in-place: local import of the block
    h = pod.f0.handoff.stats()
    assert h["fallbacks"] == {"serialize_error": 1}
    assert h["shipped"] == 0


def test_fallback_transfer_fault(pod):
    # the remote dies between pick and ship: the heartbeat is still
    # fresh, so the pick succeeds and the send itself bounces
    pod.hub.kill(1)
    got = [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    assert got == pod.refs[0]
    h = pod.f0.handoff.stats()
    assert h["fallbacks"] == {"transfer_fault": 1}
    assert h["shipped"] == 0


def test_fallback_remote_error(pod):
    class Broken:
        def generate_step(self, prompt, **kw):
            raise RuntimeError("remote pool down")
            yield  # pragma: no cover

    pod.f1.handoff.attach_local(Broken())
    got = [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    assert got == pod.refs[0]
    h = pod.f0.handoff.stats()
    assert h["fallbacks"] == {"remote_error": 1}
    assert h["shipped"] == 1  # the block made it over before the failure


def test_host_death_mid_relay_drains_token_exact(pod):
    """The host-death drain: the remote goes silent after 2 relayed
    tokens, the origin's relay times out and resumes locally AFTER the
    delivered tokens — the full stream stays bit-identical."""
    orig = pod.hub._handlers[0]
    seen = [0]

    def silent_death(src, kind, payload):
        if kind == "pod.tok":
            seen[0] += 1
            if seen[0] > 2:
                return
        elif kind == "pod.end":
            return
        orig(src, kind, payload)

    pod.hub._handlers[0] = silent_death
    pod.f0.handoff.relay_timeout_s = 2.0  # don't wait 30s on the corpse
    got = [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    assert got == pod.refs[0]  # zero dropped streams, token-exact
    h = pod.f0.handoff.stats()
    assert h["fallbacks"] == {"relay_timeout": 1}
    assert h["relayed_tokens"] == 2


def test_every_fallback_kind_is_counted(pod, monkeypatch):
    """One sweep over every degradation the ladder defines: each lands on
    the local plan with an identical stream and its own counter."""
    prompt, kw = JOBS[0]
    ref = pod.refs[0]

    faults.arm("pod.handoff", exc=faults.FaultError, times=1)
    assert [t for t, _ in pod.co.generate_step(prompt, **kw)] == ref

    with monkeypatch.context() as m:
        m.setattr(KVPageBlock, "to_bytes",
                  lambda self: (_ for _ in ()).throw(RuntimeError("x")))
        assert [t for t, _ in pod.co.generate_step(prompt, **kw)] == ref

    pod.f1.handoff.attach_local(
        type("B", (), {"generate_step": lambda self, p, **k:
                       (_ for _ in ()).throw(RuntimeError("down"))})())
    assert [t for t, _ in pod.co.generate_step(prompt, **kw)] == ref

    pod.hub.kill(1)
    assert [t for t, _ in pod.co.generate_step(prompt, **kw)] == ref

    assert pod.f0.handoff.stats()["fallbacks"] == {
        "handoff_fault": 1, "serialize_error": 1,
        "remote_error": 1, "transfer_fault": 1,
    }


# ------------------------------------------------------------ pod autoscaler


class _Ctrl:
    """Fake FleetAutoscaler: fixed pressure/headroom, records nudges."""

    def __init__(self, pressure=0.0, spawnable=0, drainable=0, slots=4):
        self._p = pressure
        self._spawnable = spawnable
        self._drainable = drainable
        self.actions = []
        self.rs = SimpleNamespace(stats=lambda: (slots, 0, 0))

    def pressure(self):
        return self._p

    def headroom(self):
        return {"live": 1, "spawnable": self._spawnable,
                "drainable": self._drainable}

    def spawn_one(self):
        self.actions.append("spawn")
        return "spawn"

    def drain_one(self):
        self.actions.append("drain")
        return "drain"


def _fleet_info(pressure, spawnable=0, drainable=0, slots=4):
    return {"pressure": pressure, "slots": slots, "live": 1,
            "spawnable": spawnable, "drainable": drainable}


def test_autoscaler_spawns_on_least_loaded_host():
    clk = [0.0]
    hub = LoopbackHub(clock=lambda: clk[0])
    t0, t1 = hub.register(0), hub.register(1)
    ctrl = _Ctrl(pressure=0.8, spawnable=1)
    a = PodAutoscaler(0, t0, [ctrl], heartbeat_timeout_s=5.0,
                      clock=lambda: clk[0])
    # the peer is hotter and has no headroom: WE are the spawn target
    t1.publish({"fleet": _fleet_info(0.95)})
    out = a.tick()
    assert out["action"] == "spawn" and ctrl.actions == ["spawn"]
    assert out["pod_pressure"] >= a.scale_up_pressure
    # a less-loaded peer WITH headroom takes the next one — not us
    t1.publish({"fleet": _fleet_info(0.76, spawnable=1)})
    ctrl._p = 0.95
    assert a.tick()["action"] is None and ctrl.actions == ["spawn"]


def test_autoscaler_drains_most_loaded_host():
    clk = [0.0]
    hub = LoopbackHub(clock=lambda: clk[0])
    t0, t1 = hub.register(0), hub.register(1)
    ctrl = _Ctrl(pressure=0.2, drainable=1)
    a = PodAutoscaler(0, t0, [ctrl], heartbeat_timeout_s=5.0,
                      clock=lambda: clk[0])
    # we are the most-loaded drainable host (the peer is idle, undrainable)
    t1.publish({"fleet": _fleet_info(0.05)})
    assert a.tick()["action"] == "drain" and ctrl.actions == ["drain"]
    # a hotter drainable peer sheds instead
    t1.publish({"fleet": _fleet_info(0.22, drainable=1)})
    assert a.tick()["action"] is None and ctrl.actions == ["drain"]


def test_autoscaler_declares_death_once():
    clk = [0.0]
    hub = LoopbackHub(clock=lambda: clk[0])
    t0, t1 = hub.register(0), hub.register(1)
    deaths = []
    a = PodAutoscaler(0, t0, [_Ctrl(pressure=0.5)], heartbeat_timeout_s=5.0,
                      on_host_death=deaths.append, clock=lambda: clk[0])
    t1.publish({"fleet": _fleet_info(0.5)})
    assert a.tick()["dead"] == []
    clk[0] += 6.0  # heartbeat goes stale past the timeout
    assert a.tick()["dead"] == [1]
    a.tick()
    assert deaths == [1]  # fired exactly once
    assert a.state()["deaths_detected"] == 1


def test_pod_fleet_death_reflected_in_pod_stats(engines):
    clk = [0.0]
    hub = LoopbackHub(clock=lambda: clk[0])
    f0 = PodFleet(0, hub.register(0), engines.co, heartbeat_timeout_s=5.0,
                  clock=lambda: clk[0])
    f1 = PodFleet(1, hub.register(1), engines.b1, heartbeat_timeout_s=5.0,
                  clock=lambda: clk[0])
    try:
        f0.tick()
        f1.tick()
        assert f0.pod_stats()["hosts"]["1"]["alive"]
        clk[0] += 6.0
        f0.tick()
        st = f0.pod_stats()
        assert not st["hosts"]["1"]["alive"]
        assert st["autoscaler"]["dead_hosts"] == [1]
        assert st["host_deaths"] == 1
    finally:
        f0.close(close_local=False)
        f1.close(close_local=False)
        engines.co.pod = None


# ------------------------------------------------------------- observability


def test_pod_metrics_render(pod):
    [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    faults.arm("pod.handoff", exc=faults.FaultError, times=1)
    [t for t, _ in pod.co.generate_step(JOBS[0][0], **JOBS[0][1])]
    text = ServingMetrics(pod_stats_fn=pod.f0.pod_stats).render()
    assert "mst_pod_hosts 2" in text
    assert 'mst_pod_host_alive{host="0"} 1' in text
    assert 'mst_pod_host_alive{host="1"} 1' in text
    assert 'mst_pod_heartbeat_age_seconds{host="1"}' in text
    assert 'mst_weight_store_trees{host="0"}' in text
    assert 'mst_fleet_size{host="0"}' in text
    assert "mst_pod_handoff_total 1" in text
    assert "mst_pod_handoff_bytes_total" in text
    assert 'mst_pod_handoff_fallbacks_total{kind="handoff_fault"} 1' in text
    assert 'mst_pod_handoff_ms{quantile="0.5"}' in text
    # each family is TYPEd exactly once — a duplicate breaks scrapers
    types = [ln for ln in text.splitlines() if ln.startswith("# TYPE ")]
    assert len(types) == len(set(types))


def test_pod_metrics_absent_on_single_host():
    assert "mst_pod_" not in ServingMetrics().render()
    assert "mst_pod_" not in ServingMetrics(
        pod_stats_fn=lambda: None).render()


def test_pod_metrics_never_500():
    def broken():
        raise RuntimeError("pod stats exploded")

    text = ServingMetrics(pod_stats_fn=broken).render()
    assert "mst_pod_" not in text  # the guard drops the partial block


def test_health_pod_block(pod):
    import http.client

    from mlx_sharding_tpu.server.openai_api import ModelProvider, make_server

    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider.generator = SimpleNamespace()
    provider.pod_fleet = pod.f0
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert payload["pod"]["host_id"] == 0
        assert set(payload["pod"]["hosts"]) == {"0", "1"}
        # a broken pod surface must never take /health down
        provider.pod_fleet = SimpleNamespace(
            pod_stats=lambda: (_ for _ in ()).throw(RuntimeError("x")))
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert "pod" not in payload
    finally:
        srv.shutdown()


# ------------------------------------------------- capacity-aware sharing


def _provider(replicas=2, disagg=False, multihost=False, mode="auto"):
    from mlx_sharding_tpu.server.openai_api import ModelProvider

    p = ModelProvider.__new__(ModelProvider)
    p.shared_weights = mode
    p.replicas = replicas
    p.disagg = disagg
    p.multihost = multihost
    return p


def test_shared_weights_auto_prices_kv_headroom(monkeypatch):
    W = 100 * 2**20
    # budget 500 MiB/slice, 3 replicas: W*(N+1)=400 MiB < 500 MiB — the
    # forfeited KV headroom outweighs the saved uploads, keep private
    monkeypatch.setenv("MST_DEVICE_MEMORY_BYTES", str(500 * 2**20))
    p = _provider(replicas=3)
    assert p._shared_weights_on(weight_bytes=W, want=3, per=1,
                                n_devices=8) is False
    # budget 300 MiB/slice: 400 MiB >= 300 MiB — sharing wins
    monkeypatch.setenv("MST_DEVICE_MEMORY_BYTES", str(300 * 2**20))
    assert p._shared_weights_on(weight_bytes=W, want=3, per=1,
                                n_devices=8) is True


def test_shared_weights_auto_edges(monkeypatch):
    W = 100 * 2**20
    monkeypatch.setenv("MST_DEVICE_MEMORY_BYTES", str(500 * 2**20))
    # a grid too small for want private slices forces sharing regardless
    assert _provider(replicas=4)._shared_weights_on(
        weight_bytes=W, want=4, per=4, n_devices=8) is True
    # unknown budget: auto keeps the legacy always-share-for-fleet rule
    monkeypatch.delenv("MST_DEVICE_MEMORY_BYTES", raising=False)
    assert _provider(replicas=3)._shared_weights_on(
        weight_bytes=W, want=3, per=1, n_devices=8) is True
    # explicit modes bypass the pricing entirely
    monkeypatch.setenv("MST_DEVICE_MEMORY_BYTES", str(500 * 2**20))
    assert _provider(mode="off")._shared_weights_on(
        weight_bytes=W, want=3, per=1, n_devices=8) is False
    assert _provider(mode="on")._shared_weights_on(
        weight_bytes=W, want=3, per=1, n_devices=8) is True
    # not a fleet / SPMD multihost: nothing to share
    assert _provider(replicas=1)._shared_weights_on(
        weight_bytes=W, want=1, per=1, n_devices=8) is False
    assert _provider(multihost=True)._shared_weights_on(
        weight_bytes=W, want=3, per=1, n_devices=8) is False


# ---------------------------------------------------------- gloo acceptance


@pytest.mark.slow
def test_gloo_two_process_acceptance():
    """The module's own acceptance demo over real gloo collectives: one
    packed tree per host aliased by two replicas, a cross-host handoff
    bit-identical to monolithic serving, and fault + host-death drains
    with zero dropped streams."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(rank):
        return subprocess.Popen(
            [sys.executable, "-m", "mlx_sharding_tpu.pod",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(rank)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )

    r1 = spawn(1)
    r0 = spawn(0)
    try:
        out = r0.communicate(timeout=240)[0].decode()
    finally:
        r0.kill()
        r1.kill()
    lines = [ln for ln in out.splitlines() if ln.startswith("{")]
    assert lines, f"rank0 printed no report:\n{out[-2000:]}"
    report = json.loads(lines[-1])
    assert report["ok"] is True, report
    assert r0.returncode == 0
    for host in ("0", "1"):
        w = report["hosts"][host]["weights"]
        assert w["trees"] == 1 and w["refs"] >= 2
    assert report["handoff"]["match"] and report["handoff"]["shipped"] >= 1
    assert report["fault_sweep"]["fallbacks"]["handoff_fault"] == 1
    assert report["host_death"]["match"]
    assert report["host_death"]["dropped_streams"] == 0
