"""Request-lifecycle tracing (tracing.py): flight recorder semantics,
Chrome ``trace_event`` export, fault-site post-mortems, the /admin/trace
HTTP surface, and the composed-stack acceptance timeline.

The cost contract is tested from both ends: ``--trace off`` adds zero
recorder state even while faults fire and real requests stream (the
static half of the same contract is mstcheck rule MST112), and with
tracing on, one timeline spans the full disagg + prefix-store +
cold-spill + async-sched path with no unexplained gaps and a span-level
TTFT that matches the client's measurement."""

import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis.lifecycle import KNOWN_FAULT_SITES
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.disagg import DisaggCoordinator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.prefix_store import PrefixStore
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.tracing import (
    MAX_SNAPSHOTS,
    MAX_SPANS_PER_TRACE,
    SPAN_TYPES,
    RequestTrace,
    Tracer,
)
from tests.helpers import hard_timeout

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)


@pytest.fixture(autouse=True)
def _reset():
    yield
    faults.disarm()
    tracing.configure("off")


# ------------------------------------------------------------ unit layer
def test_off_mode_never_allocates():
    t = Tracer(mode="off")
    assert not t.enabled
    assert t.begin("r") is None
    t.finish(None)  # None-tolerant teardown
    s = t.stats()
    assert s["live"] == 0 and s["ring"] == 0 and s["begun"] == 0


def test_sampling_is_deterministic_one_in_n():
    t = Tracer(mode="sample", sample_n=4)
    got = [t.begin(f"r{i}") for i in range(12)]
    assert [i for i, g in enumerate(got) if g is not None] == [0, 4, 8]
    assert t.stats()["begun"] == 12 and t.stats()["sampled"] == 3


def test_ring_is_bounded_and_lookup_spans_live_and_ring():
    t = Tracer(mode="on", buffer=4)
    live = t.begin("still-live")
    for i in range(10):
        tr = t.begin(f"r{i}")
        tr.add("prefill", 0.0, 1.0)
        t.finish(tr)
    s = t.stats()
    assert s["ring"] == 4 and s["live"] == 1
    assert t.get("r3") is None  # cycled out of the ring
    assert t.get("r9")["done"] is True
    assert t.get("still-live")["done"] is False
    assert t.get("nope") is None and t.export_request("nope") is None
    t.finish(live)


def test_span_cap_truncates_instead_of_growing():
    tr = RequestTrace("r")
    for _ in range(MAX_SPANS_PER_TRACE + 5):
        tr.add("decode_tick", 0.0, 1.0)
    f = tr.freeze()
    assert len(f["spans"]) == MAX_SPANS_PER_TRACE
    assert f["dropped"] == 5


def test_bind_tolerates_none_and_restores():
    assert tracing.current() is None
    tr = RequestTrace("r")
    with tracing.bind(tr):
        assert tracing.current() is tr
        with tracing.bind(None):
            assert tracing.current() is None
        assert tracing.current() is tr
    assert tracing.current() is None


def test_chrome_export_shape():
    """One process per request, one named lane per span type, ph=X spans
    with microsecond ts/dur, ph=i marks — the contract chrome://tracing
    and Perfetto actually load."""
    t = Tracer(mode="on")
    tr = t.begin("req-x")
    tr.add("prefill", t.epoch + 0.01, t.epoch + 0.02, tokens=4)
    tr.point("first_token")
    t.finish(tr)
    out = t.export_request("req-x")
    evs = out["traceEvents"]
    json.dumps(out)  # must be JSON-serializable as-is
    lanes = {e["args"]["name"]: e["tid"]
             for e in evs if e["name"] == "thread_name"}
    assert set(lanes) == set(SPAN_TYPES)
    span = next(e for e in evs if e["name"] == "prefill")
    assert span["ph"] == "X"
    assert span["ts"] == pytest.approx(10000.0, abs=2.0)
    assert span["dur"] == pytest.approx(10000.0, abs=2.0)
    assert span["args"]["request_id"] == "req-x"
    assert span["tid"] == lanes["prefill"]
    mark = next(e for e in evs if e["name"] == "first_token")
    assert mark["ph"] == "i"


def test_snapshots_bounded_and_preserve_cycled_traces():
    t = Tracer(mode="on", buffer=2)
    victim = t.begin("victim")
    victim.point("fault:somewhere")
    for i in range(MAX_SNAPSHOTS + 3):
        t.snapshot(f"r{i}")
    snaps = t.snapshots()
    assert len(snaps) == MAX_SNAPSHOTS
    assert snaps[-1]["reason"] == f"r{MAX_SNAPSHOTS + 2}"
    # cycle the victim clean out of live+ring: the snapshot still serves it
    t.finish(victim)
    for i in range(3):
        t.finish(t.begin(f"filler{i}"))
    assert t.get("victim") is not None
    assert t.export_request("victim")["traceEvents"]
    dump = t.export_dump()
    assert any(s["reason"].startswith("r") for s in dump["snapshots"])


# ------------------------------------------- fault sites -> post-mortems
@pytest.mark.parametrize("site", sorted(KNOWN_FAULT_SITES))
def test_every_fault_site_stamps_timeline_and_snapshots(site):
    """For EVERY registered fault site: when the armed fault fires against
    a bound request, the victim's timeline carries the degradation mark
    and the flight recorder auto-snapshots under ``fault:<site>`` — the
    trace survives the incident even after the ring cycles."""
    tracer = tracing.configure("on", buffer=8)
    tr = tracing.begin("victim")
    faults.arm(site, exc=RuntimeError, times=1)
    with tracing.bind(tr):
        with pytest.raises(RuntimeError):
            faults.inject(site)
    assert f"fault:{site}" in tr.mark_names()
    snaps = tracer.snapshots()
    assert snaps and snaps[-1]["reason"] == f"fault:{site}"
    frozen = [f for f in snaps[-1]["traces"] if f["request_id"] == "victim"]
    assert frozen, "victim trace missing from the auto-snapshot"
    assert any(m[0] == f"fault:{site}" for m in frozen[0]["marks"])
    tracing.finish(tr)
    # and the snapshot is reachable through the Chrome dump summary
    assert "victim" in tracer.export_dump()["snapshots"][-1]["requests"]


def test_fault_firing_with_tracing_off_adds_zero_state():
    tracer = tracing.configure("off")
    faults.arm("scheduler.tick", exc=RuntimeError, times=1)
    with pytest.raises(RuntimeError):
        faults.inject("scheduler.tick")
    s = tracer.stats()
    assert s == dict(s, live=0, ring=0, snapshots=0, begun=0)


# ------------------------------------------------- composed-stack layer
def _mk_batcher(model, params, dev_idx, **kw):
    eng = PipelineEngine(
        model, params,
        make_mesh(pp=1, devices=jax.devices()[dev_idx:dev_idx + 1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=10, page_size=8,
    )
    return ContinuousBatcher(eng, decode_block=3, **kw)


@pytest.fixture(scope="module")
def composed_stack():
    """The acceptance geometry: disaggregated prefill/decode pools, a
    prefix store on the admission path, cold-slot spill with prefetch and
    the async scheduler on the decode pool."""
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    store = PrefixStore(host_bytes=64 << 20)
    decode = _mk_batcher(model, params, 1, async_sched="on", overcommit=True,
                         spill_bytes=64 << 20, spill_cold_after=2,
                         kv_prefetch="on")
    co = DisaggCoordinator(
        ReplicaSet([_mk_batcher(model, params, 0, prefix_store=store)],
                   role="prefill", prefix_store=store),
        ReplicaSet([decode], role="decode"),
        prefix_store=store,
    )
    # warm both pools (prefill, handoff, decode compiles) so the traced
    # requests measure the serving path, not first-use jit compilation —
    # same prompt length as the traced request (the first-token graph is
    # shape-bucketed) but a different first page, so the store can't
    # short-circuit the traced handoff with a full-prefix hit. Two passes
    # with DISTINCT prefixes: the second request of a geometry compiles
    # its own (slot-reuse) variant of the sampling graph, and a repeated
    # prompt would store-hit and bypass the prefill pool instead
    for lo in (1, 101):
        for _ in co.generate_step(list(range(lo, lo + 10)), max_tokens=6):
            pass
    yield co, decode
    co.close()
    store.close()


def _covered_gaps(frozen, t_start, t_end):
    """Max uncovered gap inside [t_start, t_end] given the trace's spans
    (marks count as zero-width coverage points)."""
    ivs = [(t0, t1) for _, t0, t1, _ in frozen["spans"]]
    ivs += [(t, t) for _, t, _ in frozen["marks"]]
    ivs = sorted((max(t0, t_start), min(t1, t_end)) for t0, t1 in ivs
                 if t1 >= t_start and t0 <= t_end)
    gap, cursor = 0.0, t_start
    for t0, t1 in ivs:
        if t0 > cursor:
            gap = max(gap, t0 - cursor)
        cursor = max(cursor, t1)
    return max(gap, t_end - cursor)


@hard_timeout(240)
def test_composed_stack_timeline_end_to_end(composed_stack):
    """One trace spans the whole composed path — queue wait, store lookup,
    prefill, handoff export/transfer, decode ticks — with no unexplained
    gap bigger than a scheduler tick, and the trace's own TTFT (submit
    mark to first_token mark) matches the client-measured TTFT."""
    co, _ = composed_stack
    tracer = tracing.configure("on", buffer=16)
    tr = tracing.begin("acc-1")
    t_req = time.perf_counter()
    ttft = [None]
    toks = []
    # prompt >= one page (page_size=8) so the store's LPM probe actually
    # runs and self-records its prefix_lookup span
    prompt = [3, 17, 42, 5, 9, 11, 2, 8, 4, 6]
    for t, _ in co.generate_step(prompt, max_tokens=24, _trace=tr):
        if ttft[0] is None:
            ttft[0] = time.perf_counter() - t_req
        toks.append(t)
    tracing.finish(tr)
    assert len(toks) == 24
    frozen = tracer.get("acc-1")
    assert frozen is not None and frozen["done"]
    spans = {s[0] for s in frozen["spans"]}
    marks = {m[0] for m in frozen["marks"]}
    assert {"queue_wait", "prefix_lookup", "prefill", "handoff_export",
            "handoff_transfer", "decode_tick"} <= spans
    assert {"submit", "first_token", "finish"} <= marks
    # span-level TTFT vs the client's measurement
    t_submit = next(t for n, t, _ in frozen["marks"] if n == "submit")
    t_first = next(t for n, t, _ in frozen["marks"] if n == "first_token")
    assert abs((t_first - t_submit) - ttft[0]) < 0.05
    # the timeline is contiguous: no uncovered hole bigger than a tick
    t_finish = next(t for n, t, _ in frozen["marks"] if n == "finish")
    assert _covered_gaps(frozen, t_submit, t_finish) < 0.25
    # and the whole thing exports as loadable Chrome JSON
    json.dumps(tracer.export_request("acc-1"))


@hard_timeout(240)
def test_composed_stack_spill_wake_on_timeline(composed_stack):
    """A stalled consumer cold-spills the decode slot; the same request's
    trace shows the residency round-trip: cold_spill, wake, and the
    decode ticks resuming after it."""
    co, decode = composed_stack
    tracing.configure("on", buffer=16)
    tr = tracing.begin("acc-spill")
    base = decode.spill_stats()["cold_spills"]
    stall = threading.Event()
    toks: list = []

    def consume():
        for i, (t, _) in enumerate(
                co.generate_step([7, 7, 2, 1], max_tokens=40, _trace=tr)):
            toks.append(t)
            # stall a few tokens INTO phase 2: the coordinator submits the
            # decode resume lazily on the pull after the first token, so a
            # stall at i=0 would block before the decode slot even exists
            if i == 4:
                stall.wait()

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if decode.spill_stats()["cold_spills"] > base:
            break
        time.sleep(0.02)
    assert decode.spill_stats()["cold_spills"] > base, "slot never went cold"
    stall.set()
    th.join(timeout=120)
    assert not th.is_alive(), "stream hung after wake"
    tracing.finish(tr)
    assert len(toks) == 40
    frozen = tracing.get_tracer().get("acc-spill")
    marks = [m[0] for m in frozen["marks"]]
    assert "cold_spill" in marks and "wake" in marks
    # decode kept ticking after the wake
    t_wake = next(t for n, t, _ in frozen["marks"] if n == "wake")
    assert any(n == "decode_tick" and t0 >= t_wake
               for n, t0, _, _ in frozen["spans"])


@hard_timeout(240)
def test_composed_stack_off_mode_zero_ring_growth(composed_stack):
    """The off-mode cost contract, dynamic half: real requests through the
    full composed stack leave the recorder completely untouched — no live
    traces, no ring entries, not even a begin() counted."""
    co, _ = composed_stack
    tracer = tracing.configure("off")
    toks = [t for t, _ in co.generate_step([9, 4, 4, 6], max_tokens=12)]
    assert len(toks) == 12
    s = tracer.stats()
    assert s["live"] == 0 and s["ring"] == 0 and s["begun"] == 0


# ----------------------------------------------------------- HTTP layer
@hard_timeout(240)
def test_admin_trace_endpoints(tmp_path):
    """The served surface: every response carries X-MST-Request-Id; with
    tracing on, /admin/trace/{id} replays that request as Chrome JSON
    (including sse_write spans for a streamed request), /admin/trace/dump
    returns the ring + snapshot summary, and with tracing off the
    endpoints 404 with a hint instead of an empty 200."""
    from mlx_sharding_tpu.server.openai_api import ModelProvider, make_server
    from tests.test_tokenizer_utils import ByteTokenizer

    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    batcher = _mk_batcher(model, params, 2)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", batcher, ByteTokenizer())
    tracing.configure("on", buffer=16)
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": "hi", "max_tokens": 5}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        rid = resp.getheader("X-MST-Request-Id")
        resp.read()
        assert rid
        conn.request("GET", f"/admin/trace/{rid}")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        names = {e["name"] for e in body["traceEvents"]}
        assert "prefill" in names and "decode_tick" in names

        # a streamed request records its SSE writes on the same timeline
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": "hi", "max_tokens": 4, "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        srid = resp.getheader("X-MST-Request-Id")
        resp.read()
        conn.request("GET", f"/admin/trace/{srid}")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert "sse_write" in {e["name"] for e in body["traceEvents"]}

        conn.request("GET", "/admin/trace/dump")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert "traceEvents" in body and "snapshots" in body

        conn.request("GET", "/admin/trace/not-a-request")
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 404

        tracing.configure("off")
        conn.request("GET", "/admin/trace/dump")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 404 and "--trace" in body
        conn.close()
    finally:
        srv.shutdown()
        batcher.close()
