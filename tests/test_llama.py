import jax
import pytest

pytestmark = pytest.mark.quick
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.llama import LlamaModel

TINY = dict(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=64,
)


def _tiny_model(dtype=jnp.float32, **over):
    cfg = LlamaConfig(**{**TINY, **over})
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), dtype)
    return model, params


def test_forward_shapes():
    model, params = _tiny_model()
    cache = model.make_cache(batch=2, max_seq=16, dtype=jnp.float32)
    tokens = jnp.ones((2, 5), jnp.int32)
    logits, cache = model(params, tokens, cache)
    assert logits.shape == (2, 5, 128)
    assert int(cache.offset) == 5


def test_prefill_equals_incremental_decode():
    """Feeding tokens one-by-one through the cache must produce the same
    final-position logits as a single prefill — the core KV-cache invariant."""
    model, params = _tiny_model()
    tokens = jnp.asarray([[3, 17, 42, 9, 77, 23]], jnp.int32)

    cache = model.make_cache(1, 16, jnp.float32)
    full_logits, _ = model(params, tokens, cache)

    cache = model.make_cache(1, 16, jnp.float32)
    step_logits = []
    for i in range(tokens.shape[1]):
        logits, cache = model(params, tokens[:, i : i + 1], cache)
        step_logits.append(logits[:, 0])
    got = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(
        np.asarray(full_logits), np.asarray(got), rtol=1e-4, atol=1e-4
    )


def test_causality():
    """Changing a future token must not affect earlier logits."""
    model, params = _tiny_model()
    t1 = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    t2 = jnp.asarray([[1, 2, 3, 99]], jnp.int32)
    l1, _ = model(params, t1, model.make_cache(1, 8, jnp.float32))
    l2, _ = model(params, t2, model.make_cache(1, 8, jnp.float32))
    np.testing.assert_allclose(
        np.asarray(l1[:, :3]), np.asarray(l2[:, :3]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(l1[:, 3]), np.asarray(l2[:, 3]))


def test_pipeline_stage_composition():
    """Two chained stage models == one full model (the reference's
    sharded-vs-unsharded equivalence, never actually tested there — SURVEY §4)."""
    cfg_full = LlamaConfig(**TINY)
    full = LlamaModel(cfg_full)
    params_full = full.init_params(jax.random.PRNGKey(1), jnp.float32)

    cfg0 = LlamaConfig(**{**TINY, "start_layer": 0, "end_layer": 2})
    cfg1 = LlamaConfig(**{**TINY, "start_layer": 2, "end_layer": 4})
    s0, s1 = LlamaModel(cfg0), LlamaModel(cfg1)

    # carve the full params into the two stages
    lay = params_full["layers"]
    p0 = {"embed": params_full["embed"], "layers": {k: v[:2] for k, v in lay.items()}}
    p1 = {
        "layers": {k: v[2:] for k, v in lay.items()},
        "final_norm": params_full["final_norm"],
        "lm_head": params_full["lm_head"],
    }

    tokens = jnp.asarray([[5, 6, 7]], jnp.int32)
    ref, _ = full(params_full, tokens, full.make_cache(1, 8, jnp.float32))

    h, _ = s0(p0, tokens, s0.make_cache(1, 8, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 8, jnp.float32))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), rtol=1e-4, atol=1e-4)


def test_tied_embeddings():
    model, params = _tiny_model(tie_word_embeddings=True)
    assert "lm_head" not in params
    cache = model.make_cache(1, 8, jnp.float32)
    logits, _ = model(params, jnp.ones((1, 2), jnp.int32), cache)
    assert logits.shape == (1, 2, 128)


def test_jit_decode_no_recompile_across_positions():
    model, params = _tiny_model()
    step = jax.jit(lambda p, t, c: model(p, t, c), donate_argnums=(2,))
    cache = model.make_cache(1, 16, jnp.float32)
    tok = jnp.ones((1, 1), jnp.int32)
    logits, cache = step(params, tok, cache)
    compiled_once = step._cache_size() if hasattr(step, "_cache_size") else None
    for _ in range(3):
        logits, cache = step(params, tok, cache)
    assert int(cache.offset) == 4
    if compiled_once is not None:
        assert step._cache_size() == compiled_once
