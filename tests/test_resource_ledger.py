"""Runtime leak ledger (the MST40x verifier's dynamic cross-check).

``analysis.runtime.instrument_resources()`` turns every handle kind in the
resource registry — weight leases, prefix COW leases, breaker probe
tickets, slot/page allocations, spill-tier residency, fault arms, tracing
binds — into a live-handle set, the same way ``enable_tracing()`` turns
``make_lock`` locks into a dynamic lock-order graph. The contract under
test: driving the real composed stack (prefix store + cold-spill +
breaker probes + an autoscaler-style weight-lease storm, with a fault
armed mid-flight) leaves ZERO live handles and zero anomalies at
teardown; and a seeded leak is reported by name, so the assertion has
teeth.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.analysis import runtime as mst_runtime
from mlx_sharding_tpu.analysis.resources import RUNTIME_KINDS
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.prefix_store import PrefixStore
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.weights import WeightKey, WeightStore, aliased_spawn
from tests.helpers import hard_timeout

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)

PAGE = 8
BASE = [7, 7, 2, 1, 9, 4, 4, 6, 3, 17, 42, 5, 11, 2, 2, 8]

KEY = WeightKey(checkpoint="ck", stage_bounds=(("auto", 1),),
                dtype="float32", quant="tp1", placement="pp=1|0")


class _Tree:
    weight_bytes = 100


class _StubReplica:
    """Scriptable replica: fails on demand, else yields a fixed stream."""

    concurrent = True

    def __init__(self):
        self.fail = False

    def generate_step(self, prompt_tokens, **kw):
        if self.fail:
            raise RuntimeError("injected replica crash")
        yield from [(t, None) for t in (1, 2, 3)]


@pytest.fixture()
def ledger():
    led = mst_runtime.instrument_resources()
    try:
        yield led
    finally:
        mst_runtime.deinstrument_resources()
        faults.disarm()


# ----------------------------------------------------------- ledger unit
def test_ledger_semantics(ledger):
    ledger.note_acquire("weights.lease", 1, checkpoint="ck")
    ledger.note_acquire("weights.lease", 2)
    ledger.note_release("weights.lease", 1)
    assert ledger.counts() == {"weights.lease": (2, 1)}
    assert list(ledger.live()) == [("weights.lease", 2)]
    with pytest.raises(AssertionError, match="weights.lease:2"):
        ledger.assert_clean()
    ledger.assert_clean(ignore=("weights.lease",))  # scoped escape hatch
    ledger.note_release("weights.lease", 2)
    ledger.assert_clean()


def test_ledger_records_anomalies_without_raising(ledger):
    ledger.note_acquire("tier.block", (1, "d"))
    ledger.note_acquire("tier.block", (1, "d"))   # double acquire
    ledger.note_release("tier.block", (1, "d"))
    ledger.note_release("tier.block", (1, "d"))   # double release
    assert len(ledger.anomalies()) == 2
    assert ledger.anomalies_total == 2
    with pytest.raises(AssertionError, match="double release"):
        ledger.assert_clean()


def test_anomaly_log_is_a_bounded_ring(ledger):
    n = ledger.ANOMALY_RING + 50
    for i in range(n):
        ledger.note_release("tier.block", ("ghost", i))  # never acquired
    # the ring keeps only the newest ANOMALY_RING entries...
    msgs = ledger.anomalies()
    assert len(msgs) == ledger.ANOMALY_RING
    assert str(("ghost", n - 1)) in msgs[-1]
    assert not any(str(("ghost", 0)) in m for m in msgs)
    # ...but the counter never forgets an increment
    assert ledger.anomalies_total == n


def test_ledger_anomalies_metric_in_exposition(ledger):
    from mlx_sharding_tpu.utils.observability import ServingMetrics

    ledger.note_release("tier.block", ("ghost", 0))
    text = ServingMetrics().render()
    assert "# TYPE mst_ledger_anomalies_total counter" in text
    assert "mst_ledger_anomalies_total 1" in text


def test_note_reset_filters_by_owner(ledger):
    ledger.note_acquire("scheduler.page", (10, 0))
    ledger.note_acquire("scheduler.page", (10, 1))
    ledger.note_acquire("scheduler.page", (20, 0))
    ledger.note_reset("scheduler.page", lambda k: k[0] == 10)
    assert list(ledger.live()) == [("scheduler.page", (20, 0))]
    assert ledger.counts()["scheduler.page"] == (3, 2)


def test_hooks_are_noops_when_uninstrumented():
    assert mst_runtime._RESOURCES is None
    # must not raise, must not allocate a ledger
    mst_runtime.note_acquire("weights.lease", 1)
    mst_runtime.note_release("weights.lease", 1)
    mst_runtime.note_reset("weights.lease")
    assert mst_runtime._RESOURCES is None


# ------------------------------------------------------- seeded regression
def test_seeded_leak_is_reported_by_name(ledger):
    """The assertion has teeth: a lease acquired and never released fails
    teardown naming the kind; releasing it makes the same check pass."""
    store = WeightStore()
    lease = store.acquire(KEY, _Tree)
    with pytest.raises(AssertionError, match=r"live weights\.lease"):
        ledger.assert_clean()
    lease.release()
    ledger.assert_clean()


# ---------------------------------------------------- composed-stack zero
@hard_timeout(420)
def test_composed_stack_leaves_zero_live_handles(ledger):
    """The flagship invariant: prefix-store COW + host-tier demotion +
    cold-slot spill + breaker probe cycle + a concurrent weight-lease
    storm (with a faulted spawn and a mid-flight injected lookup fault),
    and at teardown every handle kind the registry knows is back."""
    # --- autoscaler-style weight-lease storm: concurrent spawns alias
    # one tree; one spawn faults mid-construction and must self-release
    wstore = WeightStore()
    leases = [None] * 6

    def spawn(i):
        leases[i] = wstore.acquire(KEY, _Tree)

    threads = [threading.Thread(target=spawn, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    def boom(lease):
        raise RuntimeError("spawn fault")

    with pytest.raises(RuntimeError, match="spawn fault"):
        aliased_spawn(wstore, KEY, _Tree, boom)
    for ls in leases:
        ls.release()

    # --- breaker probe tickets: open, failed probe (ticket back), healed
    # probe (ticket back again)
    r0, r1 = _StubReplica(), _StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=0.15)
    r0.fail = True
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]  # failover
    time.sleep(0.2)  # half-open: next request is the probe, and it fails
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    time.sleep(0.2)
    r0.fail = False
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]  # probe heals
    assert rs.health()["status"] == "ok"

    # --- real engine: prefix store + cold spill composed on one batcher
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=8, page_size=PAGE,
    )
    store = PrefixStore(host_bytes=64 << 20)
    batcher = ContinuousBatcher(
        eng, decode_block=3, prefix_store=store, overcommit=True,
        spill_bytes=64 << 20, spill_cold_after=2, kv_prefetch="on",
    )
    try:
        # job 1 registers the hot prefix; its finish demotes the entry to
        # the host tier (tier.block put). The consumer stalls after the
        # first token so the slot goes cold and spills (more tier traffic).
        toks: list = []
        stall = threading.Event()

        def consume():
            for i, (t, _) in enumerate(
                    batcher.generate_step(BASE + [5], max_tokens=24)):
                toks.append(t)
                if i == 0:
                    stall.wait()

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if batcher.spill_stats()["cold_spills"] > 0:
                break
            time.sleep(0.02)
        assert batcher.spill_stats()["cold_spills"] > 0, "slot never cold"
        stall.set()
        th.join(timeout=90)
        assert not th.is_alive() and len(toks) == 24

        # job 2 reuses the prefix; a lookup fault injected mid-flight
        # degrades it to plain prefill (the lease paths must still balance)
        faults.arm("cache.prefix_lookup", exc=faults.FaultError, times=1)
        assert len(list(batcher.generate_step(BASE + [9],
                                              max_tokens=8))) == 8
        faults.disarm()
        # job 3, fault gone: served through the store again
        assert len(list(batcher.generate_step(BASE + [3],
                                              max_tokens=8))) == 8
    finally:
        batcher.close()
        store.close()

    # every registry kind was actually exercised...
    counts = ledger.counts()
    for kind in RUNTIME_KINDS:
        acq, rel = counts.get(kind, (0, 0))
        assert acq > 0, f"composed workload never exercised {kind}"
    # ...and every handle came back: zero live, zero anomalies
    ledger.assert_clean()
