"""Quantized memory hierarchy (ISSUE 5 tentpole): bit-exact parity for the
pipelined decode GEMV and the build-time fused projections against the
golden dequant reference, and the int8 paged-KV contracts — greedy streams
token-identical to the bf16 pool on both paged-attention paths, a bounded
per-element quantization error, and code-exact requantize-on-writeback.

Bit-exactness strategy: every operand is constructed integer-valued
(scales 1.0, biases -2^(bits-1), integer activations), so all float32
sub-dot accumulations are exact regardless of summation order and any
kernel/XLA/fused variant of the same math must agree to the last bit.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.cache import dequantize_kv, quantize_kv_rows
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.base import apply_projection_fusion
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.ops.paged_attention import paged_attention
from mlx_sharding_tpu.ops.quant import dequantize, fuse_packed, linear
from mlx_sharding_tpu.ops.quant_matmul import (
    quant_gemv_pipelined,
    quant_matmul_pallas,
)
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.scheduler import ContinuousBatcher

GS = 64


def _exact_packed(rng, out_dim, in_dim, bits):
    """A packed triple whose dequantized values are exact small integers:
    random codes, scale 1.0, bias -2^(bits-1) → values in [-2^(b-1), 2^(b-1))."""
    words = in_dim * bits // 32
    q = rng.integers(0, 2 ** 32, size=(out_dim, words), dtype=np.uint32)
    scales = np.ones((out_dim, in_dim // GS), np.float32)
    biases = np.full(
        (out_dim, in_dim // GS), -float(2 ** (bits - 1)), np.float32
    )
    return q, scales, biases


def _bitexact_case(rng, m, in_dim, out_dim, bits):
    q, s, b = _exact_packed(rng, out_dim, in_dim, bits)
    x = rng.integers(-4, 4, size=(m, in_dim)).astype(np.float32)
    dq = np.asarray(
        dequantize(jnp.asarray(q), jnp.asarray(s), jnp.asarray(b),
                   group_size=GS, bits=bits, dtype=jnp.float32)
    )
    # exact integer reference; fits fp32 exactly (|sum| << 2^24)
    want = (x.astype(np.int64) @ dq.astype(np.int64).T).astype(np.float32)
    return x, q, s, b, want


@pytest.mark.parametrize("m", [1, 8])
def test_gemv_pipelined_bitexact_vs_golden(m):
    """The double-buffered GEMV must reproduce the golden dequant matmul to
    the last bit (2 IN blocks → the prefetch/wait pipeline actually runs)."""
    rng = np.random.default_rng(20)
    x, q, s, b, want = _bitexact_case(rng, m, in_dim=512, out_dim=256, bits=4)
    got = quant_gemv_pipelined(
        jnp.asarray(x), jnp.asarray(q), jnp.asarray(s), jnp.asarray(b),
        group_size=GS, bits=4, block_out=128, block_in=256, interpret=True,
    )
    assert np.array_equal(np.asarray(got), want)


@pytest.mark.slow
@pytest.mark.parametrize("bits", [4, 8])
@pytest.mark.parametrize("m", [1, 2, 4, 8])
@pytest.mark.parametrize("in_dim,out_dim", [(512, 128), (1024, 256)])
def test_gemv_parity_matrix(bits, m, in_dim, out_dim):
    """Full sweep: pipelined GEMV and the 3-D-grid kernel, every decode M,
    both packed widths — all bit-exact vs the golden reference."""
    rng = np.random.default_rng(21)
    x, q, s, b, want = _bitexact_case(rng, m, in_dim, out_dim, bits)
    ops = [jnp.asarray(a) for a in (x, q, s, b)]
    gemv = quant_gemv_pipelined(
        *ops, group_size=GS, bits=bits, block_out=128,
        block_in=in_dim // 2, interpret=True,
    )
    grid = quant_matmul_pallas(
        *ops, group_size=GS, bits=bits, block_m=8, block_out=128,
        block_in=in_dim // 2, interpret=True,
    )
    assert np.array_equal(np.asarray(gemv), want)
    assert np.array_equal(np.asarray(grid), want)


def test_linear_gemv_dispatch_bitexact(monkeypatch):
    """ops.quant.linear with the GEMV dispatch forced through interpret
    mode (the CPU stand-in for the TPU decode path) stays bit-exact."""
    monkeypatch.setenv("MST_QMM_GEMV", "interpret")
    rng = np.random.default_rng(22)
    x, q, s, b, want = _bitexact_case(rng, 1, in_dim=512, out_dim=256, bits=4)
    packed = {"q": jnp.asarray(q), "scales": jnp.asarray(s),
              "biases": jnp.asarray(b)}
    got = linear(jnp.asarray(x), packed, GS, 4)
    assert np.array_equal(np.asarray(got), want)


def test_fused_projection_bitexact():
    """fuse_packed concatenates triples along OUT: the fused weight must
    dequantize to exactly the concatenation, and one fused matmul must be
    bit-identical to the separate projections it replaces (each fused
    output row runs the identical sub-dot sequence)."""
    rng = np.random.default_rng(23)
    in_dim = 256
    parts, denses = [], []
    for out_dim in (128, 64, 64):  # qkv-shaped GQA split
        q, s, b = _exact_packed(rng, out_dim, in_dim, bits=4)
        parts.append({"q": jnp.asarray(q), "scales": jnp.asarray(s),
                      "biases": jnp.asarray(b)})
        denses.append(np.asarray(dequantize(
            parts[-1]["q"], parts[-1]["scales"], parts[-1]["biases"],
            group_size=GS, bits=4, dtype=jnp.float32)))
    fused = fuse_packed(parts)
    assert np.array_equal(
        np.asarray(dequantize(fused["q"], fused["scales"], fused["biases"],
                              group_size=GS, bits=4, dtype=jnp.float32)),
        np.concatenate(denses, axis=0),
    )
    x = jnp.asarray(
        rng.integers(-4, 4, size=(1, in_dim)).astype(np.float32)
    )
    want = np.concatenate(
        [np.asarray(linear(x, p, GS, 4)) for p in parts], axis=-1
    )
    assert np.array_equal(np.asarray(linear(x, fused, GS, 4)), want)


def test_apply_projection_fusion_rewrites_packed_stacks():
    """The build-time rewrite: packed q/k/v and gate/up triples collapse to
    qkv_proj / gate_up_proj, originals removed; dense stacks are left
    alone (fusion is a packed-checkpoint optimization only)."""
    model = LlamaModel(LlamaConfig(**TINY))
    rng = np.random.default_rng(24)

    def triple(out_dim, in_dim):
        q, s, b = _exact_packed(rng, out_dim, in_dim, 4)
        return {"q": jnp.asarray(q), "scales": jnp.asarray(s),
                "biases": jnp.asarray(b)}

    stack = {
        "q_proj": triple(128, 64), "k_proj": triple(64, 64),
        "v_proj": triple(64, 64), "o_proj": triple(64, 128),
        "gate_proj": triple(64, 64), "up_proj": triple(64, 64),
        "down_proj": triple(64, 64),
        "input_norm": jnp.ones((64,)),
    }
    fused = apply_projection_fusion(model, stack)
    assert sorted(fused) == ["gate_up_proj", "qkv_proj"]
    assert "q_proj" not in stack and "gate_proj" not in stack
    assert stack["qkv_proj"]["q"].shape[0] == 128 + 64 + 64
    assert stack["gate_up_proj"]["q"].shape[0] == 128

    dense_stack = {"q_proj": jnp.ones((4, 8)), "k_proj": jnp.ones((4, 8)),
                   "v_proj": jnp.ones((4, 8))}
    assert apply_projection_fusion(model, dense_stack) == []
    assert "qkv_proj" not in dense_stack


# --------------------------------------------------------------- int8 KV
TINY = dict(
    vocab_size=300, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
)


def _paged_pair(kv_dtype, pp=2, attention="auto"):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(pp), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8, pool_pages=10, page_size=8,
        paged_attention=attention, kv_dtype=kv_dtype,
    )
    return ContinuousBatcher(eng, decode_block=3)


def _streams(batcher, jobs):
    # close on exit: a leaked scheduler thread skews the wedge-timing
    # tests that run after this module
    out = [None] * len(jobs)

    def work(i, prompt, kw):
        out[i] = [t for t, _ in batcher.generate_step(prompt, **kw)]

    try:
        threads = [threading.Thread(target=work, args=(i, p, kw))
                   for i, (p, kw) in enumerate(jobs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
    finally:
        batcher.close()
    assert all(r is not None for r in out)
    return out


JOBS = [
    ([3, 17, 42], dict(max_tokens=12)),
    ([9, 1, 5, 8, 2, 250, 11], dict(max_tokens=10)),
]


@pytest.mark.parametrize(
    "pp,attention",
    # gather rides the slow tier: ragged is the serving default and pins the
    # same quantize-on-writeback path; the pp=2 gather sweep is the heavy leg
    [pytest.param(2, "gather", marks=pytest.mark.slow), (1, "ragged")],
    ids=["gather", "ragged"],
)
def test_int8_kv_greedy_token_identical(pp, attention):
    """Greedy decode through the int8 pool must emit the exact token
    stream of the bf16 pool on both paged-attention paths — multi-block
    decode (block 3, 10-12 tokens) exercises quantize-on-writeback /
    scatter several times per stream. Per-element KV error is bounded by
    max|row|/254 (half an int8 step); at the tiny model's logit margins
    that perturbation never flips an argmax."""
    want, got = (
        _streams(_paged_pair(kv, pp=pp, attention=attention), JOBS)
        for kv in (None, "int8")
    )
    assert got == want


def test_int8_writeback_reuse_roundtrip():
    """Pages freed by a finished int8 stream are reused by the next one
    (quantize → scatter → dequant-read → free → reallocate): back-to-back
    serial runs through one batcher must reproduce their own streams."""
    batcher = _paged_pair("int8")
    try:
        first = [
            [t for t, _ in batcher.generate_step(p, **kw)] for p, kw in JOBS
        ]
        again = [
            [t for t, _ in batcher.generate_step(p, **kw)] for p, kw in JOBS
        ]
        assert again == first
    finally:
        batcher.close()


def test_quantize_kv_rows_error_bound_and_requant_idempotence():
    """The two numeric contracts the engine relies on: (1) per-element
    round-trip error ≤ half an int8 step = max|row-head|/254 — the
    documented tolerance behind the greedy-identical tests; (2) re-
    quantizing a dequantized row reproduces the codes exactly (the stored
    max element sits at ±127, pinning the recomputed scale), which is what
    makes the gather path's writeback of untouched rows a no-op."""
    rng = np.random.default_rng(25)
    x = (rng.standard_normal((5, 3, 4, 32)) *
         rng.uniform(0.01, 10, (5, 3, 4, 1))).astype(np.float32)
    packed = quantize_kv_rows(jnp.asarray(x))
    dq = np.asarray(dequantize_kv(packed, jnp.float32))
    amax = np.abs(x).max(axis=-1, keepdims=True)
    assert np.all(np.abs(dq - x) <= amax / 254 + 1e-8)

    repacked = quantize_kv_rows(jnp.asarray(dq))
    assert np.array_equal(np.asarray(repacked["d"]), np.asarray(packed["d"]))
    np.testing.assert_allclose(
        np.asarray(repacked["s"]), np.asarray(packed["s"]), rtol=1e-6
    )


def test_paged_attention_int8_scales_atol():
    """Op level: the fused dequant (codes × per-row scale inside the page
    read) must match attention over the explicitly dequantized pool almost
    exactly (same numbers, different fusion point), and sit within the
    quantization-noise envelope of the original f32 pool — atol 2e-2 for
    unit-variance data, documented here as the int8-KV logits tolerance."""
    rng = np.random.default_rng(26)
    m, spg, page, hkv, d = 3, 4, 8, 2, 16
    lengths = [5, 17, 32]
    n_pages = m * spg
    k_pool = rng.standard_normal((n_pages + 1, page, hkv, d)).astype(np.float32)
    v_pool = rng.standard_normal((n_pages + 1, page, hkv, d)).astype(np.float32)
    tables = np.full((m, spg), n_pages, np.int32)
    for i, ln in enumerate(lengths):
        used = -(-ln // page)
        tables[i, :used] = np.arange(i * spg, i * spg + used)
    q = rng.standard_normal((m, 4, d)).astype(np.float32)
    scale = d ** -0.5

    kq = quantize_kv_rows(jnp.asarray(k_pool))
    vq = quantize_kv_rows(jnp.asarray(v_pool))
    args = (jnp.asarray(q),)
    common = dict(interpret=False)
    fused = paged_attention(
        *args, kq["d"], vq["d"], jnp.asarray(tables),
        jnp.asarray(lengths, jnp.int32), scale,
        k_scale=kq["s"], v_scale=vq["s"], **common,
    )
    explicit = paged_attention(
        *args, dequantize_kv(kq), dequantize_kv(vq), jnp.asarray(tables),
        jnp.asarray(lengths, jnp.int32), scale, **common,
    )
    original = paged_attention(
        *args, jnp.asarray(k_pool), jnp.asarray(v_pool), jnp.asarray(tables),
        jnp.asarray(lengths, jnp.int32), scale, **common,
    )
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(explicit), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(original), atol=2e-2, rtol=0
    )
