"""mstcheck: the self-scan CI gate plus checker unit coverage.

``test_repo_self_scan`` IS the static-analysis gate: it runs every rule
family over ``mlx_sharding_tpu/`` and fails on any finding that is neither
inline-suppressed (``# mst: allow(<rule>): <reason>``) nor recorded in
``mlx_sharding_tpu/analysis/baseline.json`` — no external runner needed.
The fixture corpus in ``tests/analysis_fixtures/`` pins each rule to a
minimal known-bad snippet: exactly one finding, with the expected span.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.analysis.core import (
    DEFAULT_BASELINE,
    analyze_paths,
    load_baseline,
    main,
    write_baseline,
)
from mlx_sharding_tpu.analysis.runtime import LockOrderRecorder

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "mlx_sharding_tpu"
FIXTURES = REPO / "tests" / "analysis_fixtures"

# fixture file -> (rule, line, col) of the single expected finding
EXPECTED = {
    "mst001_bad_suppression.py": ("MST001", 6, 0),
    "mst101_host_effect.py": ("MST101", 8, 15),
    "mst102_sync_hot_path.py": ("MST102", 7, 11),
    "mst103_recompile_hazard.py": ("MST103", 9, 16),
    "mst104_double_harvest.py": ("MST104", 8, 11),
    "mst105_dense_dequant.py": ("MST105", 10, 4),
    "mst106_sync_spill.py": ("MST106", 11, 11),
    "mst107_wall_clock_deadline.py": ("MST107", 7, 22),
    "mst107_monotonic_bypass.py": ("MST107", 12, 15),
    "mst108_block_migration.py": ("MST108", 8, 10),
    "mst109_demand_import.py": ("MST109", 10, 13),
    "mst110_spawn_upload.py": ("MST110", 10, 15),
    "mst111_prefix_import.py": ("MST111", 10, 13),
    "mst201_unlocked_attr.py": ("MST201", 15, 0),
    "mst202_check_then_act.py": ("MST202", 14, 0),
    "mst203_lock_cycle.py": ("MST203", 17, 0),
    "mst301_generator_leak.py": ("MST301", 7, 8),
    "mst302_alloc_leak.py": ("MST302", 11, 12),
    "mst303_unknown_fault_site.py": ("MST303", 6, 4),
    "mst304/scheduler.py": ("MST304", 1, 0),
    "mst112_trace_hot_path.py": ("MST112", 11, 4),
    "mst113_control_plane_in_tick.py": ("MST113", 10, 21),
    "mst114_spec_policy_sync.py": ("MST114", 6, 15),
    "mst115_prefix_federation_in_tick.py": ("MST115", 10, 7),
    "mst116_latent_reconstruct_in_tick.py": ("MST116", 10, 12),
    "mst002_dead_suppression.py": ("MST002", 5, 0),
    "mst401_exception_leak.py": ("MST401", 6, 0),
    "mst402_double_release.py": ("MST402", 8, 4),
    "mst403_release_escaped.py": ("MST403", 7, 4),
    "mst404_early_return_leak.py": ("MST404", 7, 0),
    "mst501_cross_role_write.py": ("MST501", 17, 0),
    "mst502_split_lockset.py": ("MST502", 20, 0),
    "mst503_bare_container.py": ("MST503", 17, 0),
    "mst504_blocking_under_tick_lock.py": ("MST504", 21, 0),
}


# ----------------------------------------------------------- the CI gate
def test_repo_self_scan_is_clean():
    baseline = load_baseline(DEFAULT_BASELINE) if DEFAULT_BASELINE.exists() else None
    report = analyze_paths([str(PACKAGE)], baseline=baseline)
    rendered = "\n".join(f.render() for f in report.findings)
    assert not report.findings, (
        f"mstcheck found new violations in mlx_sharding_tpu/:\n{rendered}\n"
        "Fix them, add an inline '# mst: allow(<rule>): <reason>', or (for "
        "grandfathered findings only) regenerate the baseline with "
        "`python -m mlx_sharding_tpu.analysis mlx_sharding_tpu/ "
        "--write-baseline`."
    )
    assert report.files_scanned > 40  # the scan actually covered the tree


def test_static_lock_graph_is_acyclic_with_expected_edges():
    report = analyze_paths([str(PACKAGE)], baseline=None)
    edges = {(e.src, e.dst) for e in report.lock_edges}
    # metrics render() holds its lock while reading the engine's locked
    # accessors: the one cross-class ordering the stack relies on
    assert ("ServingMetrics.lock",
            "ContinuousBatcher._admission_lock") in edges
    assert ("ReplicaSet._serial_locks[*]",
            "ContinuousBatcher._admission_lock") in edges
    cycle = LockOrderRecorder().find_cycle(extra_edges=edges)
    assert cycle is None, f"static lock-order cycle: {' -> '.join(cycle)}"


def test_cli_module_exit_codes():
    # the acceptance contract, verbatim, via the real entry point; the
    # non-zero-on-findings side runs in-process (main() == 1) per fixture
    clean = subprocess.run(
        [sys.executable, "-m", "mlx_sharding_tpu.analysis",
         "mlx_sharding_tpu/"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 finding(s)" in clean.stdout


# ------------------------------------------------------- fixture corpus
@pytest.mark.parametrize("name", sorted(EXPECTED))
def test_fixture_fires_exactly_once_with_span(name):
    rule, line, col = EXPECTED[name]
    report = analyze_paths([str(FIXTURES / name)], baseline=None)
    assert len(report.findings) == 1, [f.render() for f in report.findings]
    f = report.findings[0]
    assert (f.rule, f.line, f.col) == (rule, line, col), f.render()
    # and the CLI exits non-zero on it (no baseline applies to tests/)
    assert main([str(FIXTURES / name)]) == 1


def test_every_fixture_is_covered():
    on_disk = {
        p.relative_to(FIXTURES).as_posix()
        for p in FIXTURES.rglob("*.py")
    }
    assert on_disk == set(EXPECTED)


# ------------------------------------------------- suppression workflow
def test_suppression_with_reason_is_honored(tmp_path):
    bad = tmp_path / "counter.py"
    bad.write_text(
        "import threading\n\n\n"
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._count = 0\n\n"
        "    def incr(self):\n"
        "        with self._lock:\n"
        "            self._count += 1\n\n"
        "    def snapshot(self):\n"
        "        # mst: allow(MST201): racy read is fine for a gauge\n"
        "        return self._count\n"
    )
    report = analyze_paths([str(bad)], baseline=None)
    assert report.findings == []


def test_suppression_without_reason_is_mst001(tmp_path):
    bad = tmp_path / "counter.py"
    bad.write_text(
        "import threading\n\n\n"
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._count = 0\n\n"
        "    def incr(self):\n"
        "        with self._lock:\n"
        "            self._count += 1\n\n"
        "    def snapshot(self):\n"
        "        # mst: allow(MST201)\n"
        "        return self._count\n"
    )
    report = analyze_paths([str(bad)], baseline=None)
    rules = sorted(f.rule for f in report.findings)
    # the reasonless allow does NOT silence the finding and adds MST001
    assert rules == ["MST001", "MST201"]


def test_unparseable_file_is_mst000(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n    pass\n")
    report = analyze_paths([str(bad)], baseline=None)
    assert [f.rule for f in report.findings] == ["MST000"]


# --------------------------------------------------- baseline workflow
def test_baseline_grandfathers_findings(tmp_path):
    src = (FIXTURES / "mst201_unlocked_attr.py").read_text()
    bad = tmp_path / "counter.py"
    bad.write_text(src)

    first = analyze_paths([str(bad)], baseline=None)
    assert len(first.findings) == 1

    baseline_path = tmp_path / "baseline.json"
    write_baseline(baseline_path, first.findings)
    again = analyze_paths([str(bad)], baseline=load_baseline(baseline_path))
    assert again.findings == []
    assert [f.rule for f in again.baselined] == ["MST201"]

    # the key is line-number-free: shifting the file must not invalidate it
    bad.write_text("# a new leading comment\n" + src)
    shifted = analyze_paths([str(bad)], baseline=load_baseline(baseline_path))
    assert shifted.findings == []
    assert [f.rule for f in shifted.baselined] == ["MST201"]


def test_write_baseline_cli_roundtrip(tmp_path):
    bad = tmp_path / "counter.py"
    bad.write_text((FIXTURES / "mst201_unlocked_attr.py").read_text())
    baseline_path = tmp_path / "baseline.json"

    assert main([str(bad), "--baseline", str(baseline_path),
                 "--write-baseline"]) == 0
    data = json.loads(baseline_path.read_text())
    assert data["version"] == 1 and len(data["findings"]) == 1
    assert main([str(bad), "--baseline", str(baseline_path)]) == 0
    assert main([str(bad), "--baseline", str(baseline_path),
                 "--no-baseline"]) == 1


def test_stale_baseline_entry_is_mst003_hard_error(tmp_path):
    """Fixing the grandfathered bug must surface the baseline entry as a
    hard error with the regeneration hint — never silent rot."""
    bad = tmp_path / "counter.py"
    bad.write_text((FIXTURES / "mst201_unlocked_attr.py").read_text())
    baseline_path = tmp_path / "baseline.json"
    write_baseline(baseline_path, analyze_paths([str(bad)],
                                                baseline=None).findings)
    bad.write_text("x = 1\n")  # the bug is gone; the entry goes stale
    report = analyze_paths([str(bad)], baseline=load_baseline(baseline_path),
                           baseline_path=baseline_path)
    assert [f.rule for f in report.findings] == ["MST003"]
    f = report.findings[0]
    assert f.path == str(baseline_path)
    assert "--write-baseline" in f.message and "MST201" in f.message
    assert main([str(bad), "--baseline", str(baseline_path)]) == 1


# ------------------------------------------------- incremental cache
def test_incremental_cache_reuses_and_invalidates(tmp_path):
    src = tmp_path / "m.py"
    src.write_text((FIXTURES / "mst201_unlocked_attr.py").read_text())
    cache = tmp_path / "cache.json"

    cold = analyze_paths([str(src)], baseline=None, cache_path=cache)
    assert (cold.cache_hits, cold.cache_misses) == (0, 1)
    warm = analyze_paths([str(src)], baseline=None, cache_path=cache)
    assert (warm.cache_hits, warm.cache_misses) == (1, 0)
    # cached facts reproduce the finding exactly
    assert [(f.rule, f.line, f.col) for f in warm.findings] == \
        [(f.rule, f.line, f.col) for f in cold.findings]

    src.write_text("x = 1\n")  # content hash changes -> full recheck
    fixed = analyze_paths([str(src)], baseline=None, cache_path=cache)
    assert (fixed.cache_hits, fixed.cache_misses) == (0, 1)
    assert fixed.findings == []


def test_cache_preserves_suppressions(tmp_path):
    src = tmp_path / "counter.py"
    src.write_text(
        "import threading\n\n\n"
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._count = 0\n\n"
        "    def incr(self):\n"
        "        with self._lock:\n"
        "            self._count += 1\n\n"
        "    def snapshot(self):\n"
        "        # mst: allow(MST201): racy read is fine for a gauge\n"
        "        return self._count\n"
    )
    cache = tmp_path / "cache.json"
    assert analyze_paths([str(src)], baseline=None,
                         cache_path=cache).findings == []
    warm = analyze_paths([str(src)], baseline=None, cache_path=cache)
    assert warm.cache_hits == 1 and warm.findings == []


def test_cli_json_format_reports_cache_and_registry(tmp_path, capsys):
    fixture = FIXTURES / "mst402_double_release.py"
    cache = tmp_path / "cache.json"
    assert main([str(fixture), "--format", "json",
                 "--cache", str(cache)]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["files_scanned"] == 1 and out["cache_misses"] == 1
    assert [f["rule"] for f in out["findings"]] == ["MST402"]
    kinds = {r["kind"] for r in out["resource_registry"]}
    assert {"prefix.lease", "weights.lease", "replica.probe",
            "scheduler.page"} <= kinds
    # warm run serves the same findings from the cache
    assert main([str(fixture), "--format", "json",
                 "--cache", str(cache)]) == 1
    out2 = json.loads(capsys.readouterr().out)
    assert out2["cache_hits"] == 1
    assert out2["findings"] == out["findings"]


# --------------------------------------------- MST40x path sensitivity
def test_mst40x_clean_idioms_stay_clean(tmp_path):
    """The verifier must be quiet on the repo's own disciplined shapes:
    try/finally, None-refined early return, release delegated to a helper
    (interprocedural summary), and ownership transfer via return."""
    good = tmp_path / "clean.py"
    good.write_text(
        "def protected(store, owner, digests, pages):\n"
        "    lease = store.register(owner, digests, pages, digests, 64)\n"
        "    try:\n"
        "        broadcast(pages)\n"
        "    finally:\n"
        "        lease.release()\n"
        "\n\n"
        "def optional(store, owner, digests, pages):\n"
        "    lease = store.register(owner, digests, pages, digests, 64)\n"
        "    if lease is None:\n"
        "        return None\n"
        "    try:\n"
        "        broadcast(pages)\n"
        "    finally:\n"
        "        lease.release()\n"
        "    return True\n"
        "\n\n"
        "def delegated(store, owner, digests, pages):\n"
        "    lease = store.register(owner, digests, pages, digests, 64)\n"
        "    _finish(lease)\n"
        "\n\n"
        "def _finish(lease):\n"
        "    lease.release()\n"
        "\n\n"
        "def spawn(store, owner, digests, pages, make_engine):\n"
        "    lease = store.register(owner, digests, pages, digests, 64)\n"
        "    try:\n"
        "        return make_engine(lease)\n"
        "    except BaseException:\n"
        "        lease.release()\n"
        "        raise\n"
        "\n\n"
        "def broadcast(pages):\n"
        "    raise RuntimeError\n"
    )
    report = analyze_paths([str(good)], baseline=None)
    assert report.findings == [], [f.render() for f in report.findings]
