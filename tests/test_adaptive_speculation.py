"""Adaptive speculation: n-gram drafts, per-slot window control, and the
async/disagg/fleet composition matrix.

Exactness contract: greedy streams through any speculating path — n-gram
or draft-engine drafts, sync or async ticks, batched or disagg decode
pools — are BIT-IDENTICAL to plain decode; adaptivity (window resizes,
slot disables, brownout shedding, draft faults) may only ever change
throughput, never content. The AcceptanceTracker's policy is pinned with
an injected fake clock, so every resize/disable/re-probe step in these
tests is deterministic.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.disagg import DisaggCoordinator
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.speculative import (
    AcceptanceTracker,
    NgramDraftProposer,
    NgramSpeculativeGenerator,
    SPEC_WINDOW_LADDER,
)
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.utils.observability import ServingMetrics

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2)

# repetition in the prompt gives the n-gram matcher something to chew on;
# parity must hold whether or not proposals land
JOBS = [
    ([5, 6, 7, 5, 6, 7, 5, 6], dict(max_tokens=12)),
    ([3, 17, 42], dict(max_tokens=10)),
    ([9, 1, 9, 1, 9], dict(max_tokens=8, temperature=0.9, top_p=0.85,
                           seed=321)),
]


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _engine(tiny_model, **kw):
    model, params = tiny_model
    kw.setdefault("microbatches", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("prefill_chunk", 8)
    return PipelineEngine(model, params, pipeline_mesh(1), **kw)


def _ref(tiny_model):
    model, params = tiny_model
    return Generator(model, params, max_seq=64, cache_dtype=jnp.float32,
                     prefill_chunk=8)


def _run(gen, prompt, **kw):
    return [t for t, _ in gen.generate_step(prompt, **kw)]


def _concurrent(batcher, jobs):
    results = [None] * len(jobs)

    def worker(i, prompt, kw):
        results[i] = _run(batcher, prompt, **kw)

    threads = [threading.Thread(target=worker, args=(i, p, kw))
               for i, (p, kw) in enumerate(jobs)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=300)
        assert not th.is_alive(), "generation thread hung"
    return results


# ------------------------------------------------------- proposer (host-pure)
def test_ngram_proposer_continues_most_recent_match():
    p = NgramDraftProposer(max_ngram=3)
    # trailing 2-gram (7, 8) occurred earlier, followed by 9, 10
    drafts, n = p.propose([1, 7, 8, 9, 10, 2, 7, 8], 4)
    assert n == 4
    assert drafts.tolist() == [9, 10, 2, 7]


def test_ngram_proposer_prefers_longer_context():
    p = NgramDraftProposer(max_ngram=3)
    # (5, 6) alone appears twice with different continuations; the 3-gram
    # (4, 5, 6) disambiguates to the first occurrence's continuation
    toks = [4, 5, 6, 11, 0, 5, 6, 22, 0, 4, 5, 6]
    drafts, n = p.propose(toks, 2)
    assert n == 2
    assert drafts.tolist() == [11, 0]


def test_ngram_proposer_no_match_and_padding():
    p = NgramDraftProposer()
    drafts, n = p.propose([1, 2, 3, 4, 5], 4)  # novel text: no repeat
    assert n == 0
    assert drafts.tolist() == [0, 0, 0, 0]  # token 0 pad, never -1
    # partial continuation: match at the very end of the history
    drafts, n = p.propose([9, 9, 3, 9, 9], 4)
    assert 0 < n <= 4
    assert (drafts[n:] == 0).all()


def test_ngram_proposer_window_bounds_matching():
    # min_ngram=2 so the unigram fallback can't rescue the match once the
    # (7, 7) pair has scrolled out of the 8-token ring
    p = NgramDraftProposer(window=8, min_ngram=2)
    toks = [7, 7, 5] + [1, 2, 3, 4] * 3 + [7, 7]
    drafts, n = p.propose(toks, 2)
    assert n == 0
    # same history with an unbounded window finds it
    assert NgramDraftProposer(min_ngram=2).propose(toks, 2)[1] > 0


def test_ngram_proposer_validation():
    with pytest.raises(ValueError, match="min_ngram"):
        NgramDraftProposer(max_ngram=2, min_ngram=3)
    p = NgramDraftProposer()
    assert p.propose([], 4)[1] == 0
    assert p.propose([1, 2, 3], 0)[1] == 0


# ---------------------------------------- tracker policy under a fake clock
class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_tracker_grows_on_saturation_and_shrinks_to_disable():
    clk = _Clock()
    tr = AcceptanceTracker(2, w_max=8, clock=clk)
    assert tr.window(0) == SPEC_WINDOW_LADDER[1] == 2  # bottom rung probe
    # saturating rounds walk the ladder up: 2 -> 4 -> 8 (the EWMA has to
    # converge toward the new window before the next grow fires)
    for _ in range(12):
        tr.observe(0, tr.window(0), tr.window(0))
    assert tr.window(0) == 8
    # a draft that never agrees (count=1) walks back down and disables
    for _ in range(30):
        w = tr.window(1)
        if w == 0:
            break
        tr.observe(1, w, 1)
    assert tr.window(1) == 0
    assert tr.stats()["disabled_slots"] == 1
    # slot 0 is untouched by slot 1's collapse
    assert tr.window(0) == 8


def test_tracker_reprobe_after_deadline_is_clock_driven():
    clk = _Clock()
    tr = AcceptanceTracker(1, w_max=4, probe_after_s=1.0, clock=clk)
    while tr.window(0) != 0:
        tr.observe(0, tr.window(0), 1)
    clk.now = 0.5
    assert tr.window(0) == 0  # before the deadline: still disabled
    clk.now = 1.5
    assert tr.window(0) == 2  # re-probe at the bottom rung
    # the probe gets fresh evidence: one good round keeps it alive
    tr.observe(0, 2, 2)
    assert tr.window(0) in (2, 4)


def test_tracker_reset_clears_history():
    clk = _Clock()
    tr = AcceptanceTracker(1, w_max=8, clock=clk)
    while tr.window(0) != 0:
        tr.observe(0, tr.window(0), 1)
    tr.reset(0)
    assert tr.window(0) == 2
    assert tr.ewma(0) is None


def test_tracker_determinism_same_observations_same_windows():
    def play():
        tr = AcceptanceTracker(1, w_max=8, clock=_Clock())
        seq = []
        for count in [2, 2, 4, 4, 1, 1, 1, 3, 1, 1, 1, 1]:
            w = tr.window(0)
            tr.observe(0, w, min(count, max(w, 1)))
            seq.append(w)
        return seq

    assert play() == play()


def test_tracker_brownout_sheds_lowest_acceptance_first():
    clk = _Clock()
    tr = AcceptanceTracker(4, w_max=4, clock=clk)
    # slots 1..3 proven with ascending EWMAs (slot 3 the best); slot 0
    # untouched — no evidence at all, so it sheds before any proven slot
    for s, count in zip([1, 2, 3], [2, 3, 4]):
        tr.observe(s, 2, 2)           # bottom-rung probe saturates
        tr.observe(s, 4, count)       # distinct second-round evidence
    live = [0, 1, 2, 3]
    assert all(tr.window(s) > 0 for s in live)
    wins = tr.effective_windows(live, level=2)
    shed = {s for s, w in wins.items() if w == 0}
    assert len(shed) == 2  # half the enabled slots
    assert 0 in shed  # unproven goes first
    assert 3 not in shed  # the best acceptance keeps its window
    assert tr.shed_events == 2
    # level 3: everything sheds; re-entry is not double counted
    wins = tr.effective_windows(live, level=3)
    assert all(w == 0 for w in wins.values())
    assert tr.shed_events == 4
    # pressure clears: windows return immediately (shed is not slot state)
    wins = tr.effective_windows(live, level=0)
    assert all(w > 0 for w in wins.values())
    assert tr.shed_events == 4


# ------------------------------------------ single-stream ngram generator
def test_ngram_generator_greedy_token_exact(tiny_model):
    model, params = tiny_model
    gen = NgramSpeculativeGenerator(
        model, params, spec_window_max=8, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8, clock=lambda: 0.0,
    )
    ref = _ref(tiny_model)
    for prompt, kw in [(JOBS[0][0], dict(max_tokens=12)),
                       ([3, 17, 42], dict(max_tokens=10))]:
        assert _run(gen, prompt, **kw) == _run(ref, prompt, **kw)
    st = gen.spec_stats()
    assert st["mode"] == "ngram" and st["window_max"] == 8
    assert st["rounds"] > 0
    assert 0.0 <= st["accept_rate"] <= 1.0


@pytest.mark.slow
def test_ngram_generator_sampled_deterministic_with_fake_clock(tiny_model):
    model, params = tiny_model

    def make():
        return NgramSpeculativeGenerator(
            model, params, spec_window_max=4, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8, clock=lambda: 0.0,
        )

    kw = dict(max_tokens=10, temperature=0.9, top_p=0.85, seed=11)
    assert _run(make(), [9, 1, 9, 1, 9], **kw) == \
        _run(make(), [9, 1, 9, 1, 9], **kw)


def test_ngram_generator_validation(tiny_model):
    model, params = tiny_model
    with pytest.raises(ValueError, match="spec_window_max"):
        NgramSpeculativeGenerator(model, params, spec_window_max=1)


# ------------------------------------------- scheduler: parity matrix
def _ngram_batcher(tiny_model, async_sched, microbatches=2, **kw):
    return ContinuousBatcher(
        _engine(tiny_model, microbatches=microbatches), decode_block=4,
        draft="ngram", async_sched=async_sched, spec_clock=lambda: 0.0, **kw,
    )


@pytest.mark.parametrize("async_sched", ["off", "auto"])
def test_scheduler_ngram_greedy_parity(tiny_model, async_sched):
    """Greedy streams through n-gram speculation — sync and async ticks,
    interleaved slots — are bit-identical to plain decode, and the rounds
    actually drafted (this is not vacuous off-path parity)."""
    batcher = _ngram_batcher(tiny_model, async_sched)
    try:
        assert batcher._async == (async_sched == "auto")
        ref = _ref(tiny_model)
        greedy = [j for j in JOBS if "temperature" not in j[1]]
        refs = [_run(ref, p, **kw) for p, kw in greedy]
        assert _concurrent(batcher, greedy) == refs
        st = batcher.spec_stats()
        assert st["mode"] == "ngram"
        assert st["rounds"] > 0 and st["draft_tokens"] > 0
        assert st["accepted_tokens"] >= 0
    finally:
        batcher.close()


@pytest.mark.slow
def test_scheduler_ngram_sampled_deterministic(tiny_model):
    """Seeded sampled streams under adaptive n-gram speculation: identical
    across runs of the same batcher geometry (fake spec clock pins the
    window schedule, per-slot PRNG chains pin the keys)."""
    outs = []
    for _ in range(2):
        batcher = _ngram_batcher(tiny_model, "off")
        try:
            outs.append(_run(batcher, *JOBS[2][:1], **JOBS[2][1]))
        finally:
            batcher.close()
    assert outs[0] == outs[1]


def test_scheduler_ngram_spec_draft_fault_degrades_to_plain_decode(tiny_model):
    """An armed ``spec.draft`` fault: the tick runs plain decode instead,
    the degradation is counted, and the stream stays token-exact."""
    batcher = _ngram_batcher(tiny_model, "off")
    try:
        ref = _ref(tiny_model)
        want = _run(ref, JOBS[0][0], **JOBS[0][1])
        f = faults.arm("spec.draft", exc=RuntimeError, times=3)
        assert _run(batcher, JOBS[0][0], **JOBS[0][1]) == want
        assert f.fired == 3
        assert batcher.spec_stats()["draft_faults"] == 3
    finally:
        batcher.close()


def test_scheduler_ngram_validation(tiny_model):
    model, params = tiny_model
    eng2 = PipelineEngine(model, params, pipeline_mesh(2), microbatches=2,
                          max_seq=64, cache_dtype=jnp.float32,
                          prefill_chunk=8)
    with pytest.raises(ValueError, match="pp=1"):
        ContinuousBatcher(eng2, draft="ngram")
    eng = _engine(tiny_model)
    try:
        with pytest.raises(ValueError, match="draft"):
            ContinuousBatcher(eng, draft="lookahead")
        with pytest.raises(ValueError, match="draft engine"):
            ContinuousBatcher(eng, draft="engine")  # engine needs a draft
        with pytest.raises(ValueError, match="spec_window_max"):
            ContinuousBatcher(eng, draft="ngram", spec_window_max=1)
        with pytest.raises(ValueError, match="spec_window_max"):
            ContinuousBatcher(eng, spec_window_max=4)  # no draft mode
        b = ContinuousBatcher(eng, draft="ngram")
        try:
            # ngram always runs the adaptive tracker; engine default stays
            # legacy fixed-K (pinned by test_scheduler_heavy's perfect-draft
            # accepts-K case)
            assert b.spec_tracker is not None
        finally:
            b.close()
    finally:
        eng.close()


def test_async_auto_reason_matrix(tiny_model, monkeypatch):
    """--async-sched auto must say WHY it resolved: plain decode and ngram
    lift to async, a draft engine and multi-host force sync."""
    eng = _engine(tiny_model)
    cases = [
        (dict(), True, "plain single-host decode"),
        (dict(draft="ngram"), True, "n-gram drafts are host-built"),
    ]
    for kw, want_async, phrase in cases:
        b = ContinuousBatcher(eng, **kw)
        try:
            assert b._async is want_async, kw
            assert phrase in b.async_reason
        finally:
            b.close()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    b = ContinuousBatcher(eng)
    try:
        assert not b._async
        assert "multi-host" in b.async_reason
    finally:
        b.close()
    # ngram is refused outright in multi-host serving, with the fix named
    with pytest.raises(ValueError, match="multi-host"):
        ContinuousBatcher(eng, draft="ngram")
    monkeypatch.undo()
    eng.close()
    # draft engine -> sync, and the reason names the dependency
    deng = _engine(tiny_model)
    teng = _engine(tiny_model)
    b = ContinuousBatcher(teng, draft_engine=deng)
    try:
        assert not b._async
        assert "draft engine" in b.async_reason
    finally:
        b.close()


# ----------------------------------------------- disagg decode-pool ngram
def _paged_batcher(tiny_model, **kw):
    eng = _engine(tiny_model, pool_pages=10, page_size=8)
    return ContinuousBatcher(eng, decode_block=3, **kw)


def test_disagg_decode_pool_speculates_prefill_never(tiny_model):
    """The placement rule end to end: a prefill pool that would speculate
    is refused at construction; an ngram decode pool resumes handed-off
    streams bit-exactly (prompt-lookup drafts need no draft KV, so the
    block import composes) and its rounds actually draft."""
    with pytest.raises(ValueError, match="prefill-pool replicas"):
        co = DisaggCoordinator(
            ReplicaSet([_paged_batcher(
                tiny_model, draft="ngram", spec_clock=lambda: 0.0,
            )], role="prefill"),
            ReplicaSet([_paged_batcher(tiny_model)], role="decode"),
        )
        co.close()
    co = DisaggCoordinator(
        ReplicaSet([_paged_batcher(tiny_model)], role="prefill"),
        ReplicaSet([_paged_batcher(
            tiny_model, draft="ngram", spec_clock=lambda: 0.0,
        )], role="decode"),
    )
    try:
        ref = _ref(tiny_model)
        greedy = [j for j in JOBS if "temperature" not in j[1]]
        for p, kw in greedy:
            assert _run(co, p, **kw) == _run(ref, p, **kw)
        assert co.handoff_stats()["handoffs"] >= 2
        st = co.spec_stats()
        assert st is not None and st["mode"] == "ngram"
        assert st["rounds"] > 0  # resumed streams really speculated
    finally:
        co.close()


def test_replica_set_aggregates_spec_stats(tiny_model):
    rs = ReplicaSet([
        _ngram_batcher(tiny_model, "off"),
        _ngram_batcher(tiny_model, "off"),
    ])
    try:
        _run(rs, JOBS[0][0], **JOBS[0][1])
        st = rs.spec_stats()
        assert st["mode"] == "ngram"
        assert st["rounds"] > 0
        assert st["accept_rate"] == pytest.approx(
            st["accepted_tokens"] / max(1, st["draft_tokens"])
        )
    finally:
        rs.close()
    plain = ReplicaSet([ContinuousBatcher(_engine(tiny_model))])
    try:
        assert plain.spec_stats() is None  # non-speculating fleet: absent
    finally:
        plain.close()


# --------------------------------------------------------------- /metrics
def test_metrics_expose_spec_gauges():
    class _B:
        def stats(self):
            return (2, 1, 0)

        def spec_stats(self):
            return {"mode": "ngram", "window_max": 8, "rounds": 12,
                    "draft_tokens": 40, "accepted_tokens": 25,
                    "accept_rate": 0.625, "fallback_ticks": 1,
                    "replayed_tokens": 0, "draft_faults": 2,
                    "windows": [4, 0], "disabled_slots": 1,
                    "shed_events": 3, "ewma_mean": 2.5}

    text = ServingMetrics(batcher_fn=lambda: _B()).render()
    assert 'mst_spec_enabled{mode="ngram"} 1' in text
    assert "mst_spec_window 8" in text
    assert "mst_spec_accept_rate 0.6250" in text
    assert "mst_spec_draft_tokens_total 40" in text
    assert "mst_spec_accepted_tokens_total 25" in text
    assert "mst_spec_rounds_total 12" in text
    assert "mst_spec_draft_faults_total 2" in text
    assert "mst_spec_disabled_slots 1" in text
    assert "mst_spec_shed_events_total 3" in text


def test_metrics_spec_gauges_absent_when_not_speculating():
    class _Plain:
        def stats(self):
            return (2, 0, 0)

        def spec_stats(self):
            return None  # draft='off'

    assert "mst_spec_" not in ServingMetrics(
        batcher_fn=lambda: _Plain()
    ).render()
    assert "mst_spec_" not in ServingMetrics().render()

    class _Legacy:  # pre-speculation batcher: no spec_stats at all
        def stats(self):
            return (2, 0, 0)

    assert "mst_spec_" not in ServingMetrics(
        batcher_fn=lambda: _Legacy()
    ).render()


def test_metrics_spec_gauges_never_500():
    class _Boom:
        def stats(self):
            return (2, 1, 0)

        def spec_stats(self):
            raise RuntimeError("sick batcher")

    # a sick accessor drops the engine section, never 500s the scrape
    text = ServingMetrics(batcher_fn=lambda: _Boom()).render()
    assert "mst_requests_total 0" in text
    assert "mst_spec_" not in text


# ------------------------------------------------ brownout shed (full sweep)
@pytest.mark.slow
def test_brownout_shed_keeps_streams_exact_and_counts_sheds(tiny_model):
    """Pressure level 2 mid-generation: speculation sheds per slot (lowest
    acceptance first), streams stay token-exact, and the shed is visible in
    spec_stats; clearing pressure lets windows return."""
    batcher = _ngram_batcher(tiny_model, "off", microbatches=3)
    try:
        ref = _ref(tiny_model)
        greedy = [(p, dict(kw)) for p, kw in JOBS if "temperature" not in kw]
        refs = [_run(ref, p, **kw) for p, kw in greedy]
        batcher.set_pressure(2)
        assert _concurrent(batcher, greedy) == refs
        shed_under_pressure = batcher.spec_stats()["shed_events"]
        assert shed_under_pressure > 0
        batcher.set_pressure(0)
        assert _concurrent(batcher, greedy) == refs
        st = batcher.spec_stats()
        assert st["rounds"] > 0  # speculation resumed once pressure cleared
    finally:
        batcher.close()
