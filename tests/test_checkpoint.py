"""Checkpoint loading + logits parity against HF transformers (torch CPU).

This is the sharded-vs-reference parity layer the reference never had
(SURVEY §4 (c)): a tiny random LlamaForCausalLM is saved to safetensors,
loaded through our full loader path, and must reproduce HF logits."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.loading import load_model
from mlx_sharding_tpu.ops.quant import dequantize, quantize

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

TINY_HF = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=3,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=128,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    tie_word_embeddings=False,
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny_llama")
    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(**TINY_HF)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return path, model


def test_logits_parity_full_model(hf_checkpoint):
    path, hf_model = hf_checkpoint
    tokens = [[1, 45, 99, 3, 27, 81]]

    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()

    model, params = load_model(str(path), dtype=jnp.float32)
    cache = model.make_cache(1, 32, jnp.float32)
    got, _ = model(params, jnp.asarray(tokens, jnp.int32), cache)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-3, atol=2e-3)


def test_logits_parity_two_stages(hf_checkpoint):
    """Dynamic sharding: two stages loaded from the same full checkpoint with
    injected bounds (ref shard/utils.py:36-39) chained == full model."""
    path, hf_model = hf_checkpoint
    tokens = [[5, 9, 2]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()

    s0, p0 = load_model(str(path), start_layer=0, end_layer=2, dtype=jnp.float32)
    s1, p1 = load_model(str(path), start_layer=2, end_layer=3, dtype=jnp.float32)
    assert "embed" in p0 and "embed" not in p1
    assert "lm_head" in p1 and "lm_head" not in p0

    h, _ = s0(p0, jnp.asarray(tokens, jnp.int32), s0.make_cache(1, 16, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 16, jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-3, atol=2e-3)


def test_config_injection(hf_checkpoint, tmp_path):
    path, _ = hf_checkpoint
    from mlx_sharding_tpu.loading import load_config

    cfg = load_config(path, start_layer=1, end_layer=2)
    assert cfg["start_layer"] == 1 and cfg["end_layer"] == 2


def test_quant_roundtrip_exact():
    """dequantize(quantize(w)) must hit every representable point exactly:
    w built on the quantization grid survives the round trip bit-exactly
    (SURVEY §7 hard-part (a))."""
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.1, 1.0, size=(8, 2, 1)).astype(np.float16).astype(np.float32)
    bias = rng.uniform(-1.0, 0.0, size=(8, 2, 1)).astype(np.float16).astype(np.float32)
    q = rng.integers(0, 16, size=(8, 2, 64)).astype(np.float32)
    w = (q * scale + bias).reshape(8, 128)
    packed, s, b = quantize(w, group_size=64, bits=4)
    back = np.asarray(dequantize(packed, s, b, 64, 4, jnp.float32))
    np.testing.assert_allclose(back, w, rtol=1e-2, atol=1e-2)


def test_dequant_manual_unpack():
    """Bit-layout check against manual little-endian nibble unpacking."""
    packed = np.array([[0x76543210]], np.uint32)  # nibbles 0,1,2,...,7 LSB-first
    scales = np.ones((1, 1), np.float32)
    biases = np.zeros((1, 1), np.float32)
    out = np.asarray(dequantize(packed, scales, biases, group_size=8, bits=4, dtype=jnp.float32))
    np.testing.assert_array_equal(out, [[0, 1, 2, 3, 4, 5, 6, 7]])


def test_quantized_checkpoint_load(hf_checkpoint, tmp_path):
    """An MLX-style 4-bit checkpoint (triples + config.quantization) loads
    through the dequant path and still tracks the fp32 reference closely."""
    from safetensors.numpy import load_file, save_file

    path, hf_model = hf_checkpoint
    src = load_file(next(path.glob("*.safetensors")))
    out = {}
    for name, w in src.items():
        if name.endswith(".weight") and w.ndim == 2 and "layernorm" not in name and ".norm" not in name and "embed" not in name:
            packed, s, b = quantize(w.astype(np.float32), group_size=32, bits=4)
            base = name[: -len(".weight")]
            out[name] = packed
            out[base + ".scales"] = s
            out[base + ".biases"] = b
        else:
            out[name] = w
    qdir = tmp_path / "quant"
    qdir.mkdir()
    save_file(out, qdir / "model.safetensors")
    cfg = json.loads((path / "config.json").read_text())
    cfg["quantization"] = {"group_size": 32, "bits": 4}
    (qdir / "config.json").write_text(json.dumps(cfg))

    tokens = [[7, 3, 11, 19]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(qdir), dtype=jnp.float32)
    got, _ = model(params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 16, jnp.float32))
    # 4-bit quantization error dominates; just require close tracking
    corr = np.corrcoef(np.asarray(got).ravel(), ref.ravel())[0, 1]
    assert corr > 0.98, f"quantized logits poorly correlated: {corr}"