"""Compressed-latent KV transport (ISSUE 20): shrink every byte moved.

The load-bearing properties: (1) MLA-native pools (DeepSeek-V2
``mla_cache_mode="compressed"``) export their shared latent directly —
bit-exact round-trips at a fraction of the decompressed bytes, with the
latent geometry folded into the block fingerprint so mismatched layouts
fail closed; (2) calibrated low-rank transport for GQA pools is opt-in
and bounded by the error stamped into the artifact at calibration time;
(3) every ``cache.compress`` fault degrades inside the existing counted
taxonomy — encode faults ship the block RAW, decode faults land on the
consumer's re-prefill path, streams never drop and greedy streams stay
bit-identical on every exact path; (4) the spill tier re-accounts bytes
after the flusher compresses, turning compression into spill capacity.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.kv_compress import (
    CompressError,
    KVCompressCodec,
    KVCompressMap,
    ZeroLeaf,
    calibrate_compress_map,
    load_compress_map,
)
from mlx_sharding_tpu.kv_transfer import (
    BlockIntegrityError,
    KVPageBlock,
    KVSpillTier,
    export_block,
    import_block,
)
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.pod import LoopbackHub, PodFleet, PodPrefixFederation
from mlx_sharding_tpu.prefix_store import PrefixStore
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from tests.helpers import hard_timeout, run_concurrent

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)

PAGE = 4


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


# --------------------------------------------------------------- helpers
def _dsv2_model(seed=3, layers=4, mla_cache_mode="compressed"):
    from mlx_sharding_tpu.config import DeepseekV2Config
    from mlx_sharding_tpu.models.deepseek_v2 import DeepseekV2Model

    cfg = DeepseekV2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_hidden_layers=layers,
        num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=16,
        q_lora_rank=None, qk_rope_head_dim=8, qk_nope_head_dim=16,
        v_head_dim=12, n_routed_experts=4, n_shared_experts=1,
        num_experts_per_tok=2, first_k_dense_replace=1,
        mla_cache_mode=mla_cache_mode,
    )
    model = DeepseekV2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(seed), jnp.float32)
    return model, params


def _h1_pool_cache(pool_pages=6, page=PAGE, d_lat=24):
    """A hand-built MLA-shaped pool: ONE latent head of width ``d_lat``
    in k, the dummy all-zero ``(…, 1, 1)`` v buffer the compressed cache
    mode allocates (models/deepseek_v2.py)."""
    kshape = (1, 2, pool_pages + 1, 1, page, 1, d_lat)
    k = jnp.arange(np.prod(kshape), dtype=jnp.float32).reshape(kshape)
    v = jnp.zeros(kshape[:-2] + (1, 1), jnp.float32)
    return KVCache(k=k, v=v, offset=jnp.zeros((), jnp.int32))


def _latent_codec(d_lat=24):
    return KVCompressCodec(
        "latent", num_heads=1, head_dim_k=d_lat, head_dim_v=1
    )


def _export(cache, codec=None, pages=(2, 4)):
    return export_block(
        cache, list(pages), page_size=PAGE, n_tokens=6,
        prompt=[1, 2, 3], history=[5, 6, 7], produced=3,
        resume_keys=None, resume_recent=None, codec=codec,
    )


def _zero_like(cache):
    return KVCache(
        k=jax.tree.map(jnp.zeros_like, cache.k),
        v=jax.tree.map(jnp.zeros_like, cache.v),
        offset=jnp.zeros((), jnp.int32),
    )


def _lowrank_fixture(rank=4, L=2, H=2, D=4, pool_pages=6, seed=0):
    """Pool pages drawn from an exactly-rank-``rank`` row process plus
    the map calibrated on the same process: the SVD recovers the true
    basis, so reconstruction error is wire-float16 noise, well inside
    the stamped calibration bound."""
    rng = np.random.default_rng(seed)
    basis = np.linalg.qr(rng.normal(size=(H * D, H * D)))[0][:, :rank]

    def draw(shape_rows):
        coef = rng.normal(size=shape_rows + (rank,)).astype(np.float32)
        return (coef @ basis.T).astype(np.float32)

    cal_k = draw((L, 1, 64)).reshape(L, 1, 64, H, D)
    cal_v = draw((L, 1, 64)).reshape(L, 1, 64, H, D)
    m = calibrate_compress_map(cal_k, cal_v, rank=rank)
    kshape = (1, L, pool_pages + 1, 1, PAGE, H, D)
    k = draw((1, L, pool_pages + 1, 1, PAGE)).reshape(kshape)
    v = draw((1, L, pool_pages + 1, 1, PAGE)).reshape(kshape)
    cache = KVCache(
        k=jnp.asarray(k), v=jnp.asarray(v), offset=jnp.zeros((), jnp.int32)
    )
    codec = KVCompressCodec(
        "lowrank", compress_map=m, num_heads=H, head_dim_k=D, head_dim_v=D
    )
    return cache, m, codec


# -------------------------------------------------------------- artifact
def test_map_artifact_roundtrip_truncate_and_tamper(tmp_path):
    _, m, _ = _lowrank_fixture()
    path = str(tmp_path / "map.npz")
    m.save(path)
    loaded = KVCompressMap.load(path)
    assert loaded.compress_hash == m.compress_hash
    assert loaded.meta["calibration"]["max_rel_err"] < 1e-4

    # nested-SVD truncation: exact slice, distinct layout identity
    t2 = m.truncate(2)
    assert t2.rank == 2 and t2.compress_hash != m.compress_hash
    np.testing.assert_array_equal(t2.k_down, m.k_down[:, :, :2])
    assert load_compress_map(path, rank=2).compress_hash == t2.compress_hash
    with pytest.raises(CompressError, match="rank"):
        m.truncate(99)

    # rank without a map is a flag error, not a silent no-op
    with pytest.raises(CompressError, match="kv-compress-map"):
        load_compress_map(None, rank=2)
    assert load_compress_map(None) is None

    # an edited artifact is rejected against its own stamped hash
    import json

    import numpy as _np
    with _np.load(path) as z:
        doc = {n: _np.asarray(z[n]) for n in z.files}
    doc["k_down"] = doc["k_down"] * 1.5
    with open(path, "wb") as f:
        _np.savez(f, **doc)
    with pytest.raises(CompressError, match="recalibrate"):
        KVCompressMap.load(path)
    # and a foreign-format artifact fails with the expected-format hint
    bad = str(tmp_path / "bad.npz")
    with _np.load(path) as z:
        doc2 = {n: _np.asarray(z[n]) for n in z.files}
    hdr = json.loads(bytes(doc2["header"]).decode())
    hdr["format"] = "nope"
    doc2["header"] = _np.frombuffer(
        json.dumps(hdr).encode(), _np.uint8).copy()
    with open(bad, "wb") as f:
        _np.savez(f, **doc2)
    with pytest.raises(CompressError, match="mst-kv-compress-map-v1"):
        KVCompressMap.load(bad)


def test_map_geometry_and_share_validation_hints():
    _, m, _ = _lowrank_fixture()
    with pytest.raises(CompressError, match="recalibrate"):
        m.validate_for(3, m.num_heads, m.head_dim_k, m.head_dim_v)
    with pytest.raises(CompressError, match="kv-share-map"):
        m.validate_for(m.num_layers, m.num_heads, m.head_dim_k,
                       m.head_dim_v, share_hash="aa55")


# ----------------------------------------------------- MLA-native latent
def test_latent_export_roundtrip_bitexact_and_smaller():
    src = _h1_pool_cache()
    codec = _latent_codec()
    raw = _export(src).to_host()
    blk = _export(src, codec=codec).to_host()
    assert blk.compress_kind == "latent"
    assert blk.compress_hash == codec.compress_hash
    # the dummy-V leaves left the wire: strictly fewer bytes than raw
    assert blk.nbytes < raw.nbytes
    assert all(isinstance(leaf, ZeroLeaf)
               for leaf in jax.tree.leaves(
                   blk.v_pages,
                   is_leaf=lambda x: isinstance(x, ZeroLeaf)))

    # wire round-trip + demand reconstruction: bit-exact vs the raw path
    wire = KVPageBlock.from_bytes(blk.to_bytes())
    wire.verify()
    dst_a = import_block(_zero_like(src), wire, [1, 3], codec=codec)
    dst_b = import_block(_zero_like(src), raw, [1, 3])
    for a, b in zip(jax.tree.leaves((dst_a.k, dst_a.v)),
                    jax.tree.leaves((dst_b.k, dst_b.v))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s = codec.stats()
    assert s["mode"] == "latent" and s["blocks_compressed"] == 1
    assert s["blocks_reconstructed"] == 1
    assert s["bytes_saved_total"] > 0


def test_latent_wire_tamper_rejected():
    blk = _export(_h1_pool_cache(), codec=_latent_codec()).to_host()
    data = bytearray(blk.to_bytes())
    data[len(data) // 2] ^= 0xFF
    with pytest.raises(BlockIntegrityError):
        KVPageBlock.from_bytes(bytes(data)).verify()


def test_compress_layout_mismatch_fails_closed():
    src = _h1_pool_cache()
    blk = _export(src, codec=_latent_codec()).to_host()
    # a pool with no codec cannot reconstruct the latent payload
    with pytest.raises(BlockIntegrityError, match="compress layout"):
        import_block(_zero_like(src), blk, [1, 3])
    # nor can a codec of a different latent geometry
    with pytest.raises(BlockIntegrityError, match="compress layout"):
        import_block(_zero_like(src), blk, [1, 3],
                     codec=_latent_codec(d_lat=25))


def test_latent_prefetch_stages_reconstructed_pages():
    """prefetch() on a compressed block stages the RECONSTRUCTED form, so
    the tick-side import touches only dense pages (MST116 discipline)."""
    src = _h1_pool_cache()
    codec = _latent_codec()
    blk = _export(src, codec=codec).to_host()
    blk.prefetch(codec=codec)
    assert blk.is_prefetched
    dst = import_block(_zero_like(src), blk, [1, 3], codec=codec)
    ref = import_block(_zero_like(src), _export(src).to_host(), [1, 3])
    for a, b in zip(jax.tree.leaves((dst.k, dst.v)),
                    jax.tree.leaves((ref.k, ref.v))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------- calibrated low-rank
def test_lowrank_roundtrip_within_calibrated_bound():
    src, m, codec = _lowrank_fixture()
    blk = _export(src, codec=codec).to_host()
    assert blk.compress_kind == "lowrank"
    assert np.asarray(blk.k_pages).dtype == np.float16
    assert blk.nbytes * 2 <= _export(src).to_host().nbytes

    dst = import_block(_zero_like(src), blk, [2, 4], codec=codec)
    ref = import_block(_zero_like(src), _export(src).to_host(), [2, 4])
    for a, b in zip(jax.tree.leaves((dst.k, dst.v)),
                    jax.tree.leaves((ref.k, ref.v))):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        denom = max(float(np.linalg.norm(b)), 1e-12)
        # exactly-rank-r rows: the only loss left is float16 wire noise,
        # comfortably inside the artifact's documented tolerance + eps
        assert float(np.linalg.norm(a - b)) / denom < 5e-3


def test_lowrank_block_rejected_by_other_calibration():
    src, _, codec = _lowrank_fixture(seed=0)
    _, _, other = _lowrank_fixture(seed=7)
    blk = _export(src, codec=codec).to_host()
    assert codec.compress_hash != other.compress_hash
    with pytest.raises(BlockIntegrityError, match="compress layout"):
        import_block(_zero_like(src), blk, [2, 4], codec=other)


# ------------------------------------------------------ fault degradation
def test_encode_fault_ships_block_raw():
    src = _h1_pool_cache()
    codec = _latent_codec()
    faults.arm("cache.compress", exc=faults.FaultError, times=1)
    blk = _export(src, codec=codec).to_host()
    # the block still moved — just uncompressed — and the fault counted
    assert blk.compress_kind is None and blk.is_host
    assert codec.stats()["compress_faults"] == 1
    dst = import_block(_zero_like(src), blk, [1, 3], codec=codec)
    ref = import_block(_zero_like(src), _export(src).to_host(), [1, 3])
    for a, b in zip(jax.tree.leaves((dst.k, dst.v)),
                    jax.tree.leaves((ref.k, ref.v))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_decode_fault_is_counted_integrity_error():
    src = _h1_pool_cache()
    codec = _latent_codec()
    blk = _export(src, codec=codec).to_host()
    faults.arm("cache.compress", exc=faults.FaultError, times=1)
    with pytest.raises(BlockIntegrityError, match="reconstruction"):
        import_block(_zero_like(src), blk, [1, 3], codec=codec)
    assert codec.stats()["reconstruct_faults"] == 1
    # the fault was transient: the same block imports fine afterwards
    import_block(_zero_like(src), blk, [1, 3], codec=codec)


# ------------------------------------------------------------- spill tier
def test_spill_tier_reaccounts_compressed_bytes():
    src = _h1_pool_cache()
    codec = _latent_codec()
    tier = KVSpillTier(1 << 20, flush_async=False)
    blk = _export(src, codec=codec)
    raw_nbytes = _export(src).to_host().nbytes
    assert tier.put("a", blk)
    s = tier.stats()
    # the flush compressed the payload; the budget charges WIRE bytes
    assert blk.compress_kind == "latent"
    assert s["bytes_in_use"] == blk.nbytes < raw_nbytes
    assert s["bytes_compress_saved"] == raw_nbytes - blk.nbytes
    got = tier.take("a")
    dst = import_block(_zero_like(src), got, [1, 3], codec=codec)
    ref = import_block(_zero_like(src), _export(src).to_host(), [1, 3])
    for a, b in zip(jax.tree.leaves((dst.k, dst.v)),
                    jax.tree.leaves((ref.k, ref.v))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert tier.stats()["bytes_in_use"] == 0
    tier.close()


# ----------------------------------------------------------- prefix store
def test_prefix_store_bind_compress_hash_write_once():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    store.bind_compress_hash("aa55")
    store.bind_compress_hash("aa55")  # idempotent re-bind
    with pytest.raises(ValueError, match="kv-compress-map"):
        store.bind_compress_hash("bb66")
    store.close()


def test_prefix_store_host_put_rejects_foreign_compress_layout():
    src = _h1_pool_cache()
    codec = _latent_codec()
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    store.bind_compress_hash(codec.compress_hash)
    digests = store.digests_for(list(range(4 * PAGE)))
    ours = _export(src, codec=codec).to_host()
    theirs = _export(src, codec=_latent_codec(d_lat=25)).to_host()
    raw = _export(src).to_host()
    before = store.stats()["demote_drops"]
    assert store.host_put(digests[0], ours) is True
    assert store.host_put(digests[1], raw) is True  # raw always binds
    assert store.host_put(digests[2], theirs) is False
    assert store.stats()["demote_drops"] == before + 1
    store.close()


# ------------------------------------------------------ pod federation
def _peer(keys, *, age_s=0.0, page_size=PAGE, share=None, compress=None):
    return {"info": {"prefix": {"keys": list(keys), "page_size": page_size,
                                "share": share, "compress": compress}},
            "age_s": age_s}


def _fed(store, peers):
    class _T:
        def __init__(self):
            self.sent = []
            self.respond = None

        def peers(self):
            return peers

        def send(self, host, kind, payload):
            self.sent.append((host, kind, payload))
            if self.respond is not None:
                self.respond(host, kind, payload)

    t = _T()
    return PodPrefixFederation(0, t, store, fetch_timeout_s=0.25), t


def test_federation_heartbeat_advertises_and_checks_compress_hash():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    store.bind_compress_hash("aa55")
    hexd = store.digests_for(list(range(2 * PAGE)))[-1].hex()
    fed, t = _fed(store, {
        1: _peer([hexd], compress="bb66"),   # foreign latent layout
        2: _peer([hexd], compress=None),     # raw peer: also a mismatch
    })
    assert fed.local_info()["compress"] == "aa55"
    # every advertising peer is layout-incompatible: counted skip BEFORE
    # any bytes move, and the digest is negative-cached like a miss
    assert fed._owner_for(hexd) == (None, "layout_mismatch")
    digest = store.digests_for(list(range(2 * PAGE)))[-1]
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"layout_mismatch": 1}
    assert t.sent == []
    assert fed.fetch(digest) is False  # neg-cached now
    assert fed.stats()["fallbacks"]["neg_cached"] == 1
    store.close()


def test_federation_fetch_rejects_mismatched_blob_counted():
    """The owner re-calibrated between gossip and fetch: the blob's
    compress_hash no longer matches — counted layout_mismatch, plain
    prefill, never an import of an unreconstructable payload."""
    src = _h1_pool_cache()
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    store.bind_compress_hash(_latent_codec().compress_hash)
    digest = store.digests_for(list(range(2 * PAGE)))[-1]
    hexd = digest.hex()
    fed, t = _fed(store, {
        1: _peer([hexd], compress=_latent_codec().compress_hash),
    })
    blob = _export(src, codec=_latent_codec(d_lat=25)).to_host().to_bytes()

    def respond(host, kind, payload):
        rid = pickle.loads(payload)["rid"]
        fed.handle(1, "prefix.blob",
                   pickle.dumps((rid, blob),
                                protocol=pickle.HIGHEST_PROTOCOL))

    t.respond = respond
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"layout_mismatch": 1}
    assert fed.stats()["fetches"] == 0
    store.close()


# ---------------------------------------------------------- engine wiring
@pytest.fixture(scope="module")
def tiny_llama():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _llama_engine(tiny_llama, dev_idx=0, compress_map=None, kv_dtype=None,
                  pool_pages=10):
    model, params = tiny_llama
    devices = jax.devices()
    return PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[dev_idx:dev_idx + 1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=pool_pages, page_size=8,
        kv_dtype=kv_dtype, kv_compress_map=compress_map,
    )


def _llama_map(rank=4):
    # llama TINY pool geometry: 2 layers, 2 kv heads, head_dim 8
    rng = np.random.default_rng(1)
    k = rng.normal(size=(2, 1, 32, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 1, 32, 2, 8)).astype(np.float32)
    return calibrate_compress_map(k, v, rank=rank)


def test_engine_builds_codec_mla_native():
    model, params = _dsv2_model()
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=jax.devices()[:1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=10, page_size=8,
    )
    assert eng.kv_codec is not None and eng.kv_codec.mode == "latent"
    assert eng.kv_compress_hash == eng.kv_codec.compress_hash
    assert eng.kv_compress_stats()["mode"] == "latent"
    # a map on an MLA-native pool is redundant, not silently layered
    with pytest.raises(CompressError, match="redundant"):
        PipelineEngine(
            model, params, make_mesh(pp=1, devices=jax.devices()[:1]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8, pool_pages=10, page_size=8,
            kv_compress_map=_llama_map(),
        )


def test_engine_codec_gates(tiny_llama):
    # no map, no MLA: no codec, zero behavior change
    assert _llama_engine(tiny_llama).kv_codec is None
    # a fitting map builds a lowrank codec
    eng = _llama_engine(tiny_llama, compress_map=_llama_map())
    assert eng.kv_codec.mode == "lowrank"
    assert eng.kv_compress_stats()["rank"] == 4
    # int8 pools don't compose
    with pytest.raises(CompressError, match="int8"):
        _llama_engine(tiny_llama, compress_map=_llama_map(),
                      kv_dtype="int8")
    # mis-calibrated geometry fails closed with the remediation hint
    rng = np.random.default_rng(2)
    bad = calibrate_compress_map(
        rng.normal(size=(3, 1, 16, 2, 8)).astype(np.float32),
        rng.normal(size=(3, 1, 16, 2, 8)).astype(np.float32), rank=4)
    with pytest.raises(CompressError, match="recalibrate"):
        _llama_engine(tiny_llama, compress_map=bad)


# ------------------------------------------- end-to-end stream parity
def _mla_spill_batcher(pool_pages=8, **kw):
    """Same shape as test_kv_transfer's spill harness but on the
    MLA-native DSv2 pool: each request needs 6 of 8 pages, so
    over-commit preempts — and every spilled block flushes through the
    latent codec."""
    model, params = _dsv2_model()
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=jax.devices()[:1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=pool_pages, page_size=8,
    )
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32,
                    prefill_chunk=8)
    batcher = ContinuousBatcher(
        eng, decode_block=3, overcommit=True, spill_bytes=64 << 20, **kw
    )
    return batcher, ref


MLA_JOBS = [
    ([7, 7, 2, 1], dict(max_tokens=40)),
    ([9, 4, 4, 6], dict(temperature=0.9, top_p=0.85, seed=321,
                        max_tokens=36)),
]


def _refs(ref, jobs):
    return [[t for t, _ in ref.generate_step(p, **kw)] for p, kw in jobs]


@pytest.mark.slow
@hard_timeout(300)
def test_mla_spill_preempt_resume_bitexact():
    """The tentpole acceptance (full-sweep cell, slow for the tier-1
    budget): preempted-then-resumed streams on the MLA-native pool ride
    compressed-latent spill blocks and stay bit-identical to
    never-preempted solo runs — and the codec actually moved fewer
    bytes than raw."""
    batcher, ref = _mla_spill_batcher()
    try:
        refs = _refs(ref, MLA_JOBS)
        got = run_concurrent(batcher, MLA_JOBS)
        assert got == refs
        s = batcher.spill_stats()
        assert s["preemptions"] > 0 and s["spill_hits"] > 0
        assert s["spill_fallbacks"] == 0
        cs = batcher.engine.kv_compress_stats()
        assert cs["blocks_compressed"] > 0
        assert cs["blocks_reconstructed"] > 0
        # the pool already holds the latent; the codec's own saving here
        # is just the dummy-v leaf. The big (~num_heads×) win vs a
        # full-mode pool is measured by the kv_compressed_transport bench.
        assert cs["bytes_wire_total"] < cs["bytes_raw_total"]
        assert cs["compress_faults"] == 0 and cs["reconstruct_faults"] == 0
    finally:
        batcher.close()


@pytest.mark.slow
@hard_timeout(300)
def test_mla_spill_with_compress_faults_still_exact():
    """Full-sweep cell (slow for the tier-1 budget; the quick-tier
    encode/decode fault units + the compress_fault_handoff chaos
    scenario keep the contract gated): cache.compress armed across the
    run (encode AND decode legs hit arbitrarily): blocks ship raw /
    resumes re-prefill, counted, and every stream still matches the
    solo reference — zero drops."""
    batcher, ref = _mla_spill_batcher()
    try:
        refs = _refs(ref, MLA_JOBS)
        faults.arm("cache.compress", exc=faults.FaultError, times=2)
        got = run_concurrent(batcher, MLA_JOBS)
        faults.disarm()
        assert got == refs
        cs = batcher.engine.kv_compress_stats()
        assert cs["compress_faults"] + cs["reconstruct_faults"] >= 1
        # a second, unfaulted pass on the same pool also stays exact
        got2 = run_concurrent(batcher, MLA_JOBS)
        assert got2 == refs
    finally:
        batcher.close()


@pytest.mark.slow
@hard_timeout(300)
def test_lowrank_engine_greedy_close_and_stats(tiny_llama):
    """Full-sweep cell: the lossy low-rank path through a real batcher's
    spill/preempt flow — streams complete (no drops), the codec moved
    fewer bytes, and faults stayed zero. Token-exactness is NOT promised
    here (the path is lossy by contract; the artifact's stamped rel-err
    is the tolerance)."""
    model, params = tiny_llama
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=jax.devices()[:1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=8, page_size=8,
        kv_compress_map=_llama_map(rank=12),
    )
    batcher = ContinuousBatcher(eng, decode_block=3, overcommit=True,
                                spill_bytes=64 << 20)
    try:
        got = run_concurrent(batcher, MLA_JOBS)
        assert all(len(toks) > 0 for toks in got)
        s = batcher.spill_stats()
        assert s["preemptions"] > 0
        cs = eng.kv_compress_stats()
        assert cs["blocks_compressed"] > 0
        assert cs["bytes_wire_total"] < cs["bytes_raw_total"]
        assert cs["compress_faults"] == 0 and cs["reconstruct_faults"] == 0
    finally:
        batcher.close()


@pytest.mark.slow
@hard_timeout(300)
def test_mla_federation_end_to_end_compressed_blob_bitexact():
    """Full-sweep cell: pod prefix federation on MLA-native engines —
    the blob that rides the fabric is the compressed latent, the compress
    hash matches through the heartbeat check, and the continued stream
    is bit-identical to a monolithic batcher."""
    model, params = _dsv2_model()

    def mk_host(dev_idx, with_store=True):
        eng = PipelineEngine(
            model, params,
            make_mesh(pp=1, devices=jax.devices()[dev_idx:dev_idx + 1]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8, pool_pages=10, page_size=8,
        )
        store = PrefixStore(host_bytes=1 << 20) if with_store else None
        return ContinuousBatcher(eng, decode_block=3,
                                 prefix_store=store), store

    base = [7, 7, 2, 1, 9, 4, 4, 6, 3, 17, 42, 5, 11, 2, 2, 8]
    b_a, store_a = mk_host(0)
    b_b, store_b = mk_host(1 % len(jax.devices()))
    mono, _ = mk_host(2 % len(jax.devices()), with_store=False)
    hub = LoopbackHub()
    f_a = PodFleet(0, hub.register(0), b_a, prefix_store=store_a)
    f_b = PodFleet(1, hub.register(1), b_b, prefix_store=store_b)
    try:
        assert store_a.compress_hash is not None
        assert store_a.compress_hash == store_b.compress_hash
        list(b_a.generate_step(base + [5], max_tokens=12))
        assert store_a.stats()["demotions"] >= 1
        f_a.tick()
        f_b.tick()
        assert f_a.prefix.local_info()["compress"] == store_a.compress_hash
        got = [t for t, _ in b_b.generate_step(base + [9], max_tokens=12)]
        ref = [t for t, _ in mono.generate_step(base + [9], max_tokens=12)]
        assert got == ref
        sb = f_b.prefix.stats()
        assert sb["fetches"] == 1 and sb["fetch_bytes"] > 0
        assert sb["fallbacks"].get("layout_mismatch", 0) == 0
    finally:
        f_a.close(close_local=False)
        f_b.close(close_local=False)
        b_a.close()
        b_b.close()
        mono.close()
