"""Ragged paged-attention parity matrix (ISSUE 1 tentpole). Op level: the
Pallas kernel (interpret mode) and the fused-XLA fallback must both match a
straight-line numpy reference over uneven lengths, page-boundary offsets,
empty slots, and GQA/MQA head layouts. Engine level: a mixed-length
continuous-batching run on the ragged path must be token-exact vs the
gather path and vs the serial generator."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.ops.paged_attention import (
    kernel_eligible,
    paged_attention,
)
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.scheduler import ContinuousBatcher

PAGE = 8
SPG = 4  # slot pages — virtual max of 32 positions per slot


def _make_case(rng, lengths, hq, hkv, dk, dv):
    """Build a pool where each slot owns distinct pages for its live prefix
    and the scratch page (last pool id) past it, exactly like
    init_cache_paged lays tables out. Returns arrays plus a dense per-slot
    (S, Hkv, D) view for the reference."""
    m = len(lengths)
    n_pages = m * SPG
    k_pool = rng.standard_normal((n_pages + 1, PAGE, hkv, dk), np.float32)
    v_pool = rng.standard_normal((n_pages + 1, PAGE, hkv, dv), np.float32)
    tables = np.full((m, SPG), n_pages, np.int32)  # scratch everywhere
    for i, ln in enumerate(lengths):
        used = -(-ln // PAGE)
        tables[i, :used] = np.arange(i * SPG, i * SPG + used)
    q = rng.standard_normal((m, hq, dk), np.float32)
    dense_k = k_pool[tables].reshape(m, SPG * PAGE, hkv, dk)
    dense_v = v_pool[tables].reshape(m, SPG * PAGE, hkv, dv)
    return q, k_pool, v_pool, tables, dense_k, dense_v


def _ref(q, dense_k, dense_v, lengths, scale):
    """Per-slot numpy softmax attention over the first length rows."""
    m, hq, dk = q.shape
    hkv, dv = dense_k.shape[2], dense_v.shape[3]
    g = hq // hkv
    out = np.zeros((m, hq, dv), np.float32)
    for i, ln in enumerate(lengths):
        if ln == 0:
            continue  # inactive slot: contract is zeros
        for h in range(hq):
            k = dense_k[i, :ln, h // g]  # (ln, dk)
            v = dense_v[i, :ln, h // g]
            s = (k @ q[i, h]) * scale
            p = np.exp(s - s.max())
            out[i, h] = (p / p.sum()) @ v
    return out


# lengths hit: mid-page, exact one-page boundary, exact two-page boundary,
# empty slot, uneven multi-page, completely full slot
LENGTHS = [5, PAGE, 2 * PAGE, 0, 27, SPG * PAGE]


@pytest.mark.parametrize(
    "hq,hkv", [(4, 4), (4, 2), (4, 1)], ids=["mha", "gqa", "mqa"]
)
@pytest.mark.parametrize("interpret", [False, True], ids=["xla", "kernel"])
def test_op_parity_matrix(hq, hkv, interpret):
    rng = np.random.default_rng(0)
    dk = dv = 16
    scale = dk ** -0.5
    q, k_pool, v_pool, tables, dense_k, dense_v = _make_case(
        rng, LENGTHS, hq, hkv, dk, dv
    )
    want = _ref(q, dense_k, dense_v, LENGTHS, scale)
    got = paged_attention(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(LENGTHS, jnp.int32), scale,
        interpret=interpret,
    )
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_op_parity_uneven_head_dims_xla():
    """dv != dk (MLA-shaped) rides the XLA path on CPU."""
    rng = np.random.default_rng(1)
    lengths = [3, 11, 0]
    q, k_pool, v_pool, tables, dense_k, dense_v = _make_case(
        rng, lengths, hq=2, hkv=2, dk=24, dv=12
    )
    scale = 24 ** -0.5
    want = _ref(q, dense_k, dense_v, lengths, scale)
    got = paged_attention(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lengths, jnp.int32), scale,
    )
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_op_sliding_window_and_softcap_stay_xla():
    """Softcap / window force the fallback (kernel_eligible says no) and the
    window semantics match a masked reference."""
    assert not kernel_eligible(64, 64, 30.0, None, None, interpret=True)
    assert not kernel_eligible(64, 64, None, 4, None, interpret=True)
    rng = np.random.default_rng(2)
    lengths = [13, 7]
    window = 4
    q, k_pool, v_pool, tables, dense_k, dense_v = _make_case(
        rng, lengths, hq=2, hkv=1, dk=16, dv=16
    )
    scale = 0.25
    # reference: only the last `window` positions before the query survive
    clipped = []
    for i, ln in enumerate(lengths):
        lo = max(0, ln - window)
        dk_i = np.zeros_like(dense_k[i])
        dk_i[lo:ln] = dense_k[i, lo:ln]
        clipped.append((lo, ln))
    want = np.zeros((2, 2, 16), np.float32)
    for i, (lo, ln) in enumerate(clipped):
        for h in range(2):
            k = dense_k[i, lo:ln, 0]
            v = dense_v[i, lo:ln, 0]
            s = (k @ q[i, h]) * scale
            p = np.exp(s - s.max())
            want[i, h] = (p / p.sum()) @ v
    got = paged_attention(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lengths, jnp.int32), scale,
        sliding_window=window,
    )
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_kernel_env_opt_out(monkeypatch):
    monkeypatch.setenv("MST_PAGED_KERNEL", "0")
    assert not kernel_eligible(64, 64, None, None, None, interpret=True)
    monkeypatch.setenv("MST_PAGED_KERNEL", "1")
    assert kernel_eligible(64, 64, None, None, None, interpret=True)


# ---------------------------------------------------------------- engine ---

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def _make_engine(paged_attention):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=10, page_size=8, paged_attention=paged_attention,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return eng, ref


def _concurrent(batcher, jobs):
    results = [None] * len(jobs)

    def work(i, prompt, kw):
        results[i] = [t for t, _ in batcher.generate_step(prompt, **kw)]

    threads = [
        threading.Thread(target=work, args=(i, p, kw))
        for i, (p, kw) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert all(r is not None for r in results)
    return results


def test_engine_auto_resolves_ragged():
    eng, _ = _make_engine("auto")
    assert eng.paged_attention == "ragged"


def test_engine_ragged_requires_supported_wiring():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    with pytest.raises(ValueError, match="pp=1"):
        PipelineEngine(
            model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
            pool_pages=10, page_size=8, paged_attention="ragged",
        )


def test_engine_mixed_length_cb_parity_ragged_vs_gather():
    """The acceptance criterion: identical token streams from the ragged and
    gather paths on a mixed-length concurrent run, both matching the serial
    generator. Lengths straddle page boundaries on purpose."""
    rng = np.random.default_rng(7)
    jobs = []
    for i, plen in enumerate([3, 8, 13, 17]):  # mid/boundary/multi-page
        prompt = [int(t) for t in rng.integers(1, 300, size=plen)]
        jobs.append(
            (prompt, dict(max_tokens=int(6 + 3 * i), seed=i, temperature=0.5))
        )

    streams = {}
    for path in ("ragged", "gather"):
        eng, ref = _make_engine(path)
        assert eng.paged_attention == path
        batcher = ContinuousBatcher(eng, decode_block=3)
        try:
            streams[path] = _concurrent(batcher, jobs)
            stats = batcher.kv_read_stats()
            assert stats is not None and stats[0] == path
            assert stats[2] > 0  # bytes-read accounting registered ticks
        finally:
            batcher.close()
        if path == "ragged":
            want = [
                [t for t, _ in ref.generate_step(p, **kw)] for p, kw in jobs
            ]
            assert streams[path] == want

    assert streams["ragged"] == streams["gather"]
