"""Data-parallel serving (replicas.py): independent engine replicas behind
a least-loaded dispatcher. Streams must match what each replica would
produce solo; concurrent requests land on different replicas."""

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def _build(pp, n_replicas, concurrent=1):
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    devices = jax.devices()
    per = pp
    engines = []
    for i in range(n_replicas):
        eng = PipelineEngine(
            model, params,
            make_mesh(pp=pp, devices=devices[i * per : (i + 1) * per]),
            microbatches=concurrent, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        if concurrent > 1:
            eng = ContinuousBatcher(eng, decode_block=4)
        engines.append(eng)
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return ReplicaSet(engines), ref


from tests.helpers import run_concurrent as _concurrent_runs  # noqa: E402


def test_two_replicas_parity_and_balance():
    """2 replicas x pp2: concurrent requests split across replicas and each
    stream equals the solo engine's output."""
    rs, ref = _build(pp=2, n_replicas=2)
    try:
        jobs = [
            ([3, 17, 42], dict(max_tokens=8, seed=1)),
            ([9, 9, 31], dict(max_tokens=8, temperature=0.7, seed=2)),
        ]
        got = _concurrent_runs(rs, jobs)
        for (p, kw), toks in zip(jobs, got):
            assert toks == [t for t, _ in ref.generate_step(p, **kw)]
        assert rs.served == [1, 1]  # least-loaded routing split the pair
        slots, active, queued = rs.stats()
        assert slots == 2 and active == 0 and queued == 0
    finally:
        rs.close()


def test_replicated_batchers():
    """2 replicas each running 2-slot continuous batching: 4 interleaved
    requests, all token-exact vs the serial generator."""
    rs, ref = _build(pp=1, n_replicas=2, concurrent=2)
    try:
        jobs = [
            ([3, 17], dict(max_tokens=6, seed=i + 1, temperature=0.6))
            for i in range(4)
        ]
        got = _concurrent_runs(rs, jobs)
        for (p, kw), toks in zip(jobs, got):
            assert toks == [t for t, _ in ref.generate_step(p, **kw)]
        assert sum(rs.served) == 4 and max(rs.served) <= 3
        slots, _, _ = rs.stats()
        assert slots == 4  # 2 replicas x 2 slots aggregate on /metrics
    finally:
        rs.close()


def test_provider_wiring(tmp_path):
    """ModelProvider --replicas path end-to-end from a real checkpoint."""
    from tests.make_tiny_checkpoint import make_tiny_checkpoint
    from mlx_sharding_tpu.replicas import ReplicaSet as RS
    from mlx_sharding_tpu.server.openai_api import ModelProvider

    ckpt = str(make_tiny_checkpoint(tmp_path / "ckpt"))
    provider = ModelProvider(
        ckpt, num_stages=2, replicas=2, max_seq=64, prefill_chunk=16,
        cache_dtype=jnp.float32, trust_remote_paths=True,
    )
    try:
        assert isinstance(provider.generator, RS)
        toks = [
            t for t, _ in provider.generator.generate_step(
                [3, 5, 7], max_tokens=5, seed=1
            )
        ]
        assert len(toks) == 5
    finally:
        provider.generator.close()
