"""Data-parallel serving (replicas.py): independent engine replicas behind
a least-loaded dispatcher. Streams must match what each replica would
produce solo; concurrent requests land on different replicas."""

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def _build(pp, n_replicas, concurrent=1):
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    devices = jax.devices()
    per = pp
    engines = []
    for i in range(n_replicas):
        eng = PipelineEngine(
            model, params,
            make_mesh(pp=pp, devices=devices[i * per : (i + 1) * per]),
            microbatches=concurrent, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        if concurrent > 1:
            eng = ContinuousBatcher(eng, decode_block=4)
        engines.append(eng)
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return ReplicaSet(engines), ref


from tests.helpers import run_concurrent as _concurrent_runs  # noqa: E402


def test_two_replicas_parity_and_balance():
    """2 replicas x pp2: concurrent requests split across replicas and each
    stream equals the solo engine's output."""
    rs, ref = _build(pp=2, n_replicas=2)
    try:
        jobs = [
            ([3, 17, 42], dict(max_tokens=8, seed=1)),
            ([9, 9, 31], dict(max_tokens=8, temperature=0.7, seed=2)),
        ]
        got = _concurrent_runs(rs, jobs)
        for (p, kw), toks in zip(jobs, got):
            assert toks == [t for t, _ in ref.generate_step(p, **kw)]
        assert rs.served == [1, 1]  # least-loaded routing split the pair
        slots, active, queued = rs.stats()
        assert slots == 2 and active == 0 and queued == 0
    finally:
        rs.close()


def test_replicated_batchers():
    """2 replicas each running 2-slot continuous batching: 4 interleaved
    requests, all token-exact vs the serial generator."""
    rs, ref = _build(pp=1, n_replicas=2, concurrent=2)
    try:
        jobs = [
            ([3, 17], dict(max_tokens=6, seed=i + 1, temperature=0.6))
            for i in range(4)
        ]
        got = _concurrent_runs(rs, jobs)
        for (p, kw), toks in zip(jobs, got):
            assert toks == [t for t, _ in ref.generate_step(p, **kw)]
        assert sum(rs.served) == 4 and max(rs.served) <= 3
        slots, _, _ = rs.stats()
        assert slots == 4  # 2 replicas x 2 slots aggregate on /metrics
    finally:
        rs.close()


# ------------------------------------------------- dispatcher concurrency
# Stub replicas isolate the ROUTING properties (serial locks, aggregation,
# tie-breaking) from engine behavior, which the tests above already cover.


class _Stub:
    concurrent = True

    def __init__(self, tokens=(1, 2, 3)):
        self.tokens = list(tokens)

    def generate_step(self, prompt_tokens, **kw):
        yield from [(t, None) for t in self.tokens]


def test_serial_replica_requests_never_overlap():
    """A replica without ``concurrent`` gets a per-replica serial lock: two
    threads streaming through the same one-slot replica must interleave at
    the request level, never inside it."""
    import threading
    import time

    class Serial:
        # no `concurrent` attr: the dispatcher must serialize around us
        def __init__(self):
            self.active = 0
            self.max_active = 0
            self._lock = threading.Lock()

        def generate_step(self, prompt_tokens, **kw):
            with self._lock:
                self.active += 1
                self.max_active = max(self.max_active, self.active)
            try:
                for t in range(3):
                    time.sleep(0.01)  # widen any overlap window
                    yield (t, None)
            finally:
                with self._lock:
                    self.active -= 1

    rep = Serial()
    rs = ReplicaSet([rep])
    got = _concurrent_runs(rs, [([1], {}) for _ in range(4)])
    assert got == [[0, 1, 2]] * 4
    assert rep.max_active == 1
    assert rs.served == [4]


def test_stats_aggregation_across_replicas():
    """stats()/page_stats() sum element-wise across replica batchers; plain
    generators count as one slot and contribute no pages."""

    class WithStats(_Stub):
        def stats(self):
            return (2, 1, 3)

        def page_stats(self):
            return (10, 4, 6)

    rs = ReplicaSet([WithStats(), WithStats()])
    assert rs.stats() == (4, 2, 6)
    assert rs.page_stats() == (20, 8, 12)
    # no paged replica anywhere → no page story to report
    assert ReplicaSet([_Stub()]).page_stats() is None
    mixed = ReplicaSet([WithStats(), _Stub()])
    assert mixed.stats() == (3, 1, 3)
    assert mixed.page_stats() == (10, 4, 6)


def test_least_loaded_routing_and_ties():
    """Ties break to the lowest index; an in-flight stream tips the next
    request to the idle replica."""
    r0, r1 = _Stub(), _Stub()
    rs = ReplicaSet([r0, r1])
    # idle tie → replica 0, twice (the first request finished before the
    # second arrived, so the tie repeats)
    for _ in range(2):
        assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.served == [2, 0]
    # hold a stream open on 0 mid-iteration: the next request must go to 1
    it = rs.generate_step([1])
    next(it)
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.served == [3, 1]
    assert list(it) == [(2, None), (3, None)]  # held stream completes intact


def test_provider_wiring(tmp_path):
    """ModelProvider --replicas path end-to-end from a real checkpoint."""
    from tests.make_tiny_checkpoint import make_tiny_checkpoint
    from mlx_sharding_tpu.replicas import ReplicaSet as RS
    from mlx_sharding_tpu.server.openai_api import ModelProvider

    ckpt = str(make_tiny_checkpoint(tmp_path / "ckpt"))
    provider = ModelProvider(
        ckpt, num_stages=2, replicas=2, max_seq=64, prefill_chunk=16,
        cache_dtype=jnp.float32, trust_remote_paths=True,
    )
    try:
        assert isinstance(provider.generator, RS)
        toks = [
            t for t, _ in provider.generator.generate_step(
                [3, 5, 7], max_tokens=5, seed=1
            )
        ]
        assert len(toks) == 5
    finally:
        provider.generator.close()
