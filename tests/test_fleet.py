"""Elastic fleet controller (fleet.py) + load-aware routing (replicas.py):
score-based placement with prefix affinity and session stickiness, the
fake-clock autoscaler decision loop (hysteresis, cooldown, min/max clamps,
spawn/drain failure quarantine), the brownout ladder, Retry-After
estimation, and the server surfaces that expose all of it."""

import http.client
import json
import threading
import time

import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.fleet import BrownoutController, FleetAutoscaler
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.resilience import ReplicasUnavailableError
from mlx_sharding_tpu.scheduler import estimate_retry_after
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.utils.observability import ServingMetrics


class FakeClock:
    """Injectable monotonic clock: hysteresis/cooldown without sleeping."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float):
        self.t += dt


class _Stub:
    concurrent = True

    def __init__(self, tokens=(1, 2, 3)):
        self.tokens = list(tokens)
        self.closed = False

    def generate_step(self, prompt_tokens, **kw):
        yield from [(t, None) for t in self.tokens]

    def close(self):
        self.closed = True


class _LoadStub(_Stub):
    """Stub whose (slots, active, queued) is set by the test — the
    autoscaler's pressure signal under full control."""

    def __init__(self):
        super().__init__()
        self.load = (1, 0, 0)

    def stats(self):
        return self.load


# ----------------------------------------------------------------- routing
def test_affinity_beats_least_loaded_within_tolerance():
    rs = ReplicaSet([_Stub(), _Stub()], affinity_page=4)
    prompt = list(range(8))  # two affinity pages
    i, _ = rs._pick((), prompt=prompt)
    rs._done(i)
    assert i == 0  # ties break to the lowest index (round-robin baseline)
    assert rs.route_affinity_hits == 0  # nothing warm yet
    # replica 0 now busier — but within route_imbalance the warm prefix
    # wins over strict least-loaded (this is the affinity > round-robin
    # property: a naive alternation would bounce the prefix to replica 1)
    with rs._lock:
        rs._inflight[0] = 2
    i, _ = rs._pick((), prompt=prompt)
    rs._done(i)
    assert i == 0 and rs.route_affinity_hits == 1
    # beyond the tolerance the escape hatch takes over: load wins
    with rs._lock:
        rs._inflight[0] = rs.route_imbalance + 3
    i, _ = rs._pick((), prompt=prompt)
    rs._done(i)
    assert i == 1


def test_short_prompts_contribute_no_affinity_signal():
    rs = ReplicaSet([_Stub(), _Stub()])  # affinity_page=128 default
    assert rs._affinity_chunks([1, 2, 3]) == []
    assert rs._affinity_chunks("not tokens") == []


def test_session_stickiness_survives_drain():
    rs = ReplicaSet([_Stub(), _Stub()])
    i, _ = rs._pick((), session="alice")
    rs._done(i)
    assert i == 0
    # the session sticks even when the other replica is slightly less loaded
    with rs._lock:
        rs._inflight[0] = 2
    j, _ = rs._pick((), session="alice")
    rs._done(j)
    assert j == 0 and rs.route_sticky_hits == 1
    with rs._lock:
        rs._inflight[0] = 0
    # drain the sticky replica: the session re-maps, the request never errors
    rs.drain(0, deadline=1.0)
    k, _ = rs._pick((), session="alice")
    rs._done(k)
    assert k == 1
    rs.close()


def test_tight_ttft_disables_warm_detours():
    rs = ReplicaSet([_Stub(), _Stub()], affinity_page=4)
    prompt = list(range(8))
    i, _ = rs._pick((), prompt=prompt)
    rs._done(i)
    assert i == 0
    with rs._lock:
        rs._inflight[0] = 2
    # a tight deadline collapses the tolerance: least-loaded wins over warm
    j, _ = rs._pick((), prompt=prompt, tight=True)
    rs._done(j)
    assert j == 1


def test_queue_depth_counts_toward_load():
    class Deep(_Stub):
        def stats(self):
            return (4, 0, 9)

    rs = ReplicaSet([Deep(), _Stub()])
    i, _ = rs._pick(())
    rs._done(i)
    assert i == 1  # inflight parity, but replica 0's queue is 9 deep


def test_all_breakers_open_raises_with_retry_eta():
    class Boom:
        concurrent = True

        def generate_step(self, prompt_tokens, **kw):
            raise RuntimeError("dead")
            yield  # pragma: no cover — makes this a generator

    rs = ReplicaSet([Boom(), Boom()], breaker_threshold=1, probe_interval=5.0)
    # one request strikes out both replicas (it retries across the fleet),
    # opening both breakers; the concrete failure wins over the generic 503
    with pytest.raises(RuntimeError):
        list(rs.generate_step([1, 2, 3]))
    # next request: everything open → 503 carrying the earliest probe ETA
    with pytest.raises(ReplicasUnavailableError) as ei:
        list(rs.generate_step([1, 2, 3]))
    eta = ei.value.retry_after_s
    assert eta is not None and 0 < eta <= 5.0


# --------------------------------------------------------------- brownout
def test_brownout_escalates_immediately_steps_down_one_rung_per_dwell():
    clk = FakeClock()
    b = BrownoutController(dwell_s=5.0, clock=clk)
    assert b.observe(0.5) == 0
    assert b.observe(2.5) == 3  # straight to the top rung
    assert b.state() == {
        "level": 3, "max_tokens_cap": 96,
        "speculation_disabled": True, "speculation_shed": "all",
        "admission_tightened": True,
    }
    # pressure collapses — but de-escalation needs the dwell, one rung each
    assert b.observe(0.1) == 3
    clk.advance(5.0)
    assert b.observe(0.1) == 2
    clk.advance(5.0)
    assert b.observe(0.1) == 1
    assert b.max_tokens_cap() == 512
    clk.advance(5.0)
    assert b.observe(0.1) == 0
    assert b.max_tokens_cap() is None


def test_brownout_dwell_resets_when_pressure_returns():
    clk = FakeClock()
    b = BrownoutController(dwell_s=5.0, clock=clk)
    b.observe(1.0)  # level 1
    b.observe(0.1)  # below exit — dwell starts
    clk.advance(4.0)
    b.observe(1.0)  # pressure back above exit: dwell anchor resets
    clk.advance(4.0)
    assert b.observe(0.1) == 1  # only 0s below — no de-escalation yet


def test_brownout_validation():
    with pytest.raises(ValueError):
        BrownoutController(enter=(1.0, 0.9, 2.0))
    with pytest.raises(ValueError):
        BrownoutController(exit=(0.9, 1.3, 2.1))  # exit >= enter
    with pytest.raises(ValueError):
        FleetAutoscaler(object(), min_replicas=0)


# -------------------------------------------------------------- autoscaler
def _fleet(clk, factory=None, n=2, **kw):
    reps = [_LoadStub() for _ in range(n)]
    rs = ReplicaSet(reps)
    ctrl = FleetAutoscaler(rs, factory, clock=clk, **kw)
    return rs, reps, ctrl


def test_scale_up_hysteresis_cooldown_and_max_clamp():
    clk = FakeClock()
    spawned = []

    def factory():
        r = _LoadStub()
        spawned.append(r)
        return r

    rs, reps, ctrl = _fleet(
        clk, factory, max_replicas=3,
        scale_up_sustain_s=5.0, cooldown_s=20.0,
    )
    for r in reps:
        r.load = (1, 1, 2)  # pressure 3.0
    assert ctrl.tick()["action"] is None  # sustain window just anchored
    clk.advance(5.0)
    assert ctrl.tick()["action"] == "spawn"
    assert len(spawned) == 1 and rs.fleet_stats()["size"] == 3
    assert rs.fleet_stats()["autoscale_events"]["spawn"] == 1
    # cooldown: pressure still high, no immediate second spawn
    clk.advance(5.0)
    assert ctrl.tick()["action"] is None
    # and past the cooldown the max clamp holds the fleet at 3
    clk.advance(30.0)
    assert ctrl.tick()["action"] is None
    assert len(spawned) == 1


def test_scale_down_drains_least_loaded_and_respects_min():
    clk = FakeClock()
    rs, reps, ctrl = _fleet(
        clk, None, n=3, min_replicas=2,
        scale_down_sustain_s=10.0, cooldown_s=0.0, drain_deadline_s=0.2,
    )
    assert ctrl.tick()["action"] is None  # idle — sustain anchored
    clk.advance(10.0)
    assert ctrl.tick()["action"] == "drain"
    # all-idle tie drains the HIGHEST index (newest spawn, coldest cache)
    assert reps[2].closed and rs.fleet_stats()["size"] == 2
    # min clamp: never below the floor, however long the idle lasts
    clk.advance(60.0)
    assert ctrl.tick()["action"] is None
    assert rs.fleet_stats()["size"] == 2


def test_spawn_failure_degrades_to_static_fleet_then_recovers():
    clk = FakeClock()
    spawned = []

    def factory():
        r = _LoadStub()
        spawned.append(r)
        return r

    rs, reps, ctrl = _fleet(
        clk, factory, max_replicas=3,
        scale_up_sustain_s=5.0, cooldown_s=20.0,
    )
    for r in reps:
        r.load = (1, 1, 2)
    faults.arm("replica.spawn", exc=RuntimeError, times=1)
    try:
        ctrl.tick()
        clk.advance(5.0)
        assert ctrl.tick()["action"] == "spawn_failed"
        st = ctrl.state()
        assert st["spawn_failures"] == 1 and st["degraded"]
        assert spawned == [] and rs.fleet_stats()["size"] == 2
        assert rs.fleet_stats()["autoscale_events"]["spawn_failed"] == 1
        # the static fleet keeps serving — streams intact
        assert [t for t, _ in rs.generate_step([1, 2, 3])] == [1, 2, 3]
        # after the cooldown quarantine the retry succeeds
        clk.advance(25.0)
        ctrl.tick()  # re-anchors the sustain window
        clk.advance(5.0)
        assert ctrl.tick()["action"] == "spawn"
        assert len(spawned) == 1 and not ctrl.state()["degraded"]
    finally:
        faults.disarm()


def test_drain_failure_quarantines_and_keeps_serving():
    clk = FakeClock()
    rs, reps, ctrl = _fleet(
        clk, None, n=3, min_replicas=1,
        scale_down_sustain_s=10.0, cooldown_s=30.0, drain_deadline_s=0.2,
    )
    faults.arm("replica.drain", exc=RuntimeError, times=1)
    try:
        ctrl.tick()
        clk.advance(10.0)
        assert ctrl.tick()["action"] == "drain_failed"
        st = ctrl.state()
        assert st["drain_failures"] == 1 and st["degraded"]
        assert rs.fleet_stats()["autoscale_events"]["drain_failed"] == 1
        # the victim stays quarantined (no new routes) but is NOT retired —
        # its in-flight streams keep flowing
        per = rs.replica_stats()
        assert any(p["draining"] and not p["retired"] for p in per)
        assert [t for t, _ in rs.generate_step([1, 2, 3])] == [1, 2, 3]
    finally:
        faults.disarm()


def test_tick_fault_degrades_not_raises():
    clk = FakeClock()
    rs, reps, ctrl = _fleet(clk, None)
    faults.arm("autoscaler.tick", exc=RuntimeError, times=1)
    try:
        assert ctrl.tick() == {"error": True}
        assert ctrl.state()["tick_errors"] == 1
        assert rs.fleet_stats()["autoscale_events"]["tick_error"] == 1
        # the next tick is healthy again
        assert "pressure" in ctrl.tick()
    finally:
        faults.disarm()


def test_brownout_level_propagates_to_replicas_and_health():
    class P(_LoadStub):
        def __init__(self):
            super().__init__()
            self.pressure_seen = None

        def set_pressure(self, level):
            self.pressure_seen = level

    clk = FakeClock()
    reps = [P(), P()]
    rs = ReplicaSet(reps)
    ctrl = FleetAutoscaler(rs, None, clock=clk)
    for r in reps:
        r.load = (1, 1, 2)  # pressure 3.0 ≥ enter[2]
    assert ctrl.tick()["brownout"] == 3
    assert all(r.pressure_seen == 3 for r in reps)
    assert rs.fleet_stats()["autoscale_events"]["brownout_level_3"] == 1
    health = rs.health()
    assert health["brownout"]["level"] == 3
    assert health["autoscaler"]["ticks"] == 1


# ------------------------------------------------------------- retry-after
def test_estimate_retry_after_zero_drain_is_worst_case_ceiling():
    assert estimate_retry_after(5, [], 100.0) == 30.0
    # stale finishes (outside the window) count as zero drain too
    assert estimate_retry_after(5, [10.0], 100.0) == 30.0


def test_estimate_retry_after_tracks_drain_rate_with_clamps():
    finishes = [90.0 + i for i in range(10)]  # 1 request/s
    assert estimate_retry_after(5, finishes, 100.0) == pytest.approx(5.0)
    # a torrent of finishes clamps to the floor...
    assert estimate_retry_after(1, [99.9] * 50, 100.0) == 1.0
    # ...and a huge backlog to the ceiling
    assert estimate_retry_after(10_000, finishes, 100.0) == 30.0


# ------------------------------------------------------------ observability
def test_metrics_render_fleet_gauges():
    rs = ReplicaSet([_Stub(), _Stub()])
    rs.record_autoscale_event("spawn")
    rs.record_autoscale_event("spawn")
    rs.record_autoscale_event("drain_failed")
    rs.brownout = BrownoutController()
    text = ServingMetrics(batcher_fn=lambda: rs).render()
    assert 'mst_replica_inflight{replica="0"} 0' in text
    assert 'mst_replica_queue_depth{replica="1"} 0' in text
    assert 'mst_replica_breaker_state{replica="0"} 0' in text
    assert "mst_fleet_size 2" in text
    assert 'mst_autoscale_events_total{kind="spawn"} 2' in text
    assert 'mst_autoscale_events_total{kind="drain_failed"} 1' in text
    assert "mst_route_sticky_hits_total 0" in text
    assert "mst_route_affinity_hits_total 0" in text
    assert "mst_brownout_level 0" in text


# ------------------------------------------------------------- server glue
def _serve(provider):
    from mlx_sharding_tpu.server.openai_api import make_server

    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, port


def _provider(gen):
    from mlx_sharding_tpu.server.openai_api import ModelProvider
    from tests.test_tokenizer_utils import ByteTokenizer

    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", gen, ByteTokenizer())
    return provider


def _post(port, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/v1/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, headers, data


def test_server_maps_replicas_unavailable_to_503_with_retry_after():
    class Down:
        concurrent = True

        def generate_step(self, prompt_tokens, **kw):
            raise ReplicasUnavailableError("all open", retry_after_s=7.2)
            yield  # pragma: no cover

    srv, port = _serve(_provider(Down()))
    try:
        status, headers, body = _post(port, {"prompt": "hi", "max_tokens": 4})
        assert status == 503
        assert headers.get("Retry-After") == "7"
        assert json.loads(body)["error"]["type"] == "service_unavailable_error"
    finally:
        srv.shutdown()


def test_server_brownout_cap_header_and_session_forwarding():
    class Gen:
        concurrent = True
        supports_sessions = True

        def __init__(self):
            self.kw = None

        def generate_step(self, prompt_tokens, **kw):
            self.kw = kw
            yield from [(65, None), (66, None)]

    class FakeFleet:
        def __init__(self, brownout):
            self.brownout = brownout

    bro = BrownoutController(clock=FakeClock())
    bro.observe(1.5)  # level 2 → cap 256
    gen = Gen()
    provider = _provider(gen)
    provider.fleet = FakeFleet(bro)
    srv, port = _serve(provider)
    try:
        status, headers, _ = _post(
            port,
            {"prompt": "hi", "max_tokens": 4000, "session_id": "alice"},
        )
        assert status == 200
        assert headers.get("X-MST-Brownout-Level") == "2"
        assert headers.get("X-MST-Max-Tokens-Capped") == "256"
        assert gen.kw["max_tokens"] == 256
        assert gen.kw["_session"] == "alice"
    finally:
        srv.shutdown()


def test_admin_autoscaler_endpoint():
    class FakeFleet:
        def __init__(self):
            self.brownout = BrownoutController(clock=FakeClock())
            self.started = self.stopped = 0

        def start(self):
            self.started += 1

        def stop(self):
            self.stopped += 1

        def state(self):
            return {"running": bool(self.started and not self.stopped),
                    "ticks": 0}

    provider = _provider(_Stub())
    srv, port = _serve(provider)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/admin/autoscaler", b"{}",
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        assert resp.status == 400  # no fleet controller serving
        provider.fleet = FakeFleet()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/admin/autoscaler",
                     json.dumps({"enabled": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert provider.fleet.started == 1
        assert body["brownout"]["level"] == 0
    finally:
        srv.shutdown()


# ----------------------------------------------------------- heavy (slow)
@pytest.mark.slow
def test_autoscaler_thread_loop_spawns_under_load():
    """Real-thread elasticity sim: sustained pressure on a 2-replica fleet
    spawns a third while streams keep flowing; stop() joins cleanly."""
    reps = [_LoadStub(), _LoadStub()]
    for r in reps:
        r.load = (1, 1, 2)
    rs = ReplicaSet(reps)
    spawned = []

    def factory():
        r = _LoadStub()
        spawned.append(r)
        return r

    ctrl = FleetAutoscaler(
        rs, factory, max_replicas=3, interval_s=0.05,
        scale_up_sustain_s=0.1, cooldown_s=10.0,
    )
    ctrl.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not spawned:
            time.sleep(0.05)
        assert len(spawned) == 1
        assert [t for t, _ in rs.generate_step([1, 2, 3])] == [1, 2, 3]
        assert rs.fleet_stats()["size"] == 3
    finally:
        ctrl.stop()
        rs.close()
