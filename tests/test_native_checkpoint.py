import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.checkpoint import (
    is_native_checkpoint,
    load_native_checkpoint,
    save_native_checkpoint,
)
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.loading import load_model
from mlx_sharding_tpu.models.llama import LlamaModel

TINY = dict(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def test_roundtrip_logits_identical(tmp_path):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    tokens = jnp.asarray([[3, 7, 11]], jnp.int32)
    ref, _ = model(params, tokens, model.make_cache(1, 8, jnp.float32))

    save_native_checkpoint(tmp_path / "ck", params, cfg)
    assert is_native_checkpoint(tmp_path / "ck")
    model2, params2 = load_native_checkpoint(tmp_path / "ck", dtype=jnp.float32)
    got, _ = model2(params2, tokens, model2.make_cache(1, 8, jnp.float32))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_load_model_detects_native(tmp_path):
    cfg = LlamaConfig(**{**TINY, "start_layer": 1, "end_layer": 3})
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    save_native_checkpoint(tmp_path / "stage", params, cfg)

    model2, params2 = load_model(str(tmp_path / "stage"), dtype=jnp.float32)
    assert model2.config.start_layer == 1 and model2.config.end_layer == 3
    assert params2["layers"]["q_proj"].shape[0] == 2


def test_native_honors_requested_dtype(tmp_path):
    """A float32 request against a float32-saved checkpoint stays f32; a
    bf16 request against the same checkpoint delivers bf16 params."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(2), jnp.float32)
    save_native_checkpoint(tmp_path / "ck", params, cfg)
    _, p32 = load_model(str(tmp_path / "ck"), dtype=jnp.float32)
    assert p32["layers"]["q_proj"].dtype == jnp.float32
    _, p16 = load_model(str(tmp_path / "ck"), dtype=jnp.bfloat16)
    assert p16["layers"]["q_proj"].dtype == jnp.bfloat16


def test_native_refuses_reslice(tmp_path):
    cfg = LlamaConfig(**{**TINY, "start_layer": 0, "end_layer": 2})
    model = LlamaModel(cfg)
    save_native_checkpoint(
        tmp_path / "s", model.init_params(jax.random.PRNGKey(0), jnp.float32), cfg
    )
    with pytest.raises(ValueError, match="re-slice"):
        load_native_checkpoint(tmp_path / "s", start_layer=1, end_layer=2)
