"""Decode-throughput benchmark on the real TPU chip.

Reproduces the reference's own instrumentation definitions — generation
tok/s = (tokens-1)/decode_time, prompt tok/s, TTFT (ref: generate.py:97-122)
— on this framework's single-chip decode path, with a Llama-3.2-3B-class
model (the largest dense config that comfortably fits one v5e chip's HBM in
bf16; the BASELINE.json DeepSeek-Coder-V2-Lite config needs the 8-chip pod
this environment doesn't expose). Weights are randomly initialized on device
— decode throughput is weight-value-independent.

Beyond the headline number the run records (BENCH_DETAIL.json + stderr):
- MBU (model-bandwidth utilization): decode is HBM-bound, so effective
  bytes/s streamed (param bytes x tok/s) over the chip's peak HBM bandwidth
  is the roofline that matters; MFU is reported alongside for reference.
- Pallas kernel smoke: flash-attention (prefill + T=1 decode) and the fused
  dequant-matmul compiled for real (interpret=False) and cross-checked
  numerically against the XLA paths they replace.
- A 4-bit packed-resident decode variant (--keep-quantized path's kernel).
- An MST_FLASH_DECODE on/off A/B on the same model.

vs_baseline: BASELINE.md records no published reference numbers (the
reference publishes none). The divisor 35.0 tok/s is our documented nominal
for the reference stack (single-host MLX, Apple-silicon, 3B-class bf16
model); vs_baseline > 1.5 meets the BASELINE.json target ratio.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NOMINAL_SINGLE_HOST_MLX_TOKS = 35.0

# TPU v5e (v5 lite) public specs
V5E_PEAK_BF16_FLOPS = 197e12
V5E_PEAK_HBM_BYTES = 819e9

BENCH_MODEL = dict(
    model_type="llama",
    vocab_size=128256,
    hidden_size=3072,
    intermediate_size=8192,
    num_hidden_layers=28,
    num_attention_heads=24,
    num_key_value_heads=8,
    head_dim=128,
    tie_word_embeddings=True,
    max_position_embeddings=4096,
)

PROMPT_LEN = 64
DECODE_TOKENS = 256
MAX_SEQ = 1024

# The BASELINE.json PRIMARY config: DeepSeek-Coder-V2-Lite's public
# architecture (HF deepseek-ai/DeepSeek-Coder-V2-Lite-Instruct config.json;
# the reference deploys it as the 0-14/14-27 split,
# /root/reference/shard/utils.py:36-39). The actual checkpoint BYTES are
# unobtainable here (zero-egress environment, no local copy — see
# BASELINE.md round 5), so the headline measurement runs this real
# architecture at real scale with synthetic packed-4-bit weights: decode
# throughput is weight-value-independent (HBM bytes moved per token is the
# roofline), and the layout is byte-identical to
# load_model(keep_quantized=True) on the real 4-bit checkpoint.
DSV2_LITE = dict(
    model_type="deepseek_v2",
    vocab_size=102400,
    hidden_size=2048,
    intermediate_size=10944,
    moe_intermediate_size=1408,
    num_hidden_layers=27,
    num_attention_heads=16,
    num_key_value_heads=16,
    kv_lora_rank=512,
    q_lora_rank=None,
    qk_rope_head_dim=64,
    qk_nope_head_dim=128,
    v_head_dim=128,
    n_routed_experts=64,
    n_shared_experts=2,
    num_experts_per_tok=6,
    first_k_dense_replace=1,
    norm_topk_prob=False,
    routed_scaling_factor=1.0,
    topk_method="greedy",
    rope_theta=10000.0,
    rope_scaling=dict(
        type="yarn", factor=40,
        original_max_position_embeddings=4096,
        beta_fast=32, beta_slow=1, mscale=0.707, mscale_all_dim=0.707,
    ),
    max_position_embeddings=163840,
    quantization=dict(group_size=64, bits=4),
)

DETAIL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json")


def log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def _probe_backend(timeout: int = 60) -> bool:
    """The axon tunnel can wedge; probe it in a subprocess so a hang can't
    take the bench (and the driver) down with it."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
        # require an actual TPU: a CPU-only environment must take the
        # clearly-labeled fallback, not mislabel a CPU run as real-chip
        return proc.returncode == 0 and "TPU" in proc.stdout.upper()
    except subprocess.TimeoutExpired:
        return False


def _probe_backend_with_retries() -> bool:
    """Probe the tunnel in a short retry loop: the wedge is intermittent
    (BASELINE.md round-1/2/3 notes), but the old 15-minute budget burned
    ~10 min of a wedged round before the CPU fallback even started
    (BENCH_r05 tail: 3×300s probes). Two minutes of 60s probes catches the
    transient case; a tunnel still down after that is down for the run —
    fail over fast and let the carry-forward keep the real-chip record.
    Override with MST_BENCH_PROBE_BUDGET_S (0 = single probe, for
    tests/CI; raise it for a known-flaky real-chip window)."""
    try:
        budget = float(os.environ.get("MST_BENCH_PROBE_BUDGET_S", "120"))
    except ValueError:
        log("bad MST_BENCH_PROBE_BUDGET_S; using the 120s default")
        budget = 120.0
    start = time.monotonic()
    deadline = start + budget
    attempt = 0
    while True:
        attempt += 1
        if _probe_backend(timeout=60):
            log(f"tunnel probe ok (attempt {attempt})")
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            log(f"tunnel probe: no TPU after {attempt} attempt(s) / "
                f"{time.monotonic() - start:.0f}s budget — CPU fallback")
            return False
        time.sleep(min(30.0, max(0.0, remaining)))


def _git_commit() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() if out.returncode == 0 else "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


def _is_real_chip_detail(detail: dict) -> bool:
    """One predicate for 'this detail file came from a real TPU run' —
    shared by the carry-forward reader and the clobber guard, so a device
    repr change can never split their verdicts (and case-insensitive, so
    'TpuDevice'-style reprs still count)."""
    return "TPU" in str(detail.get("device", "")).upper()


def _detail_file_provenance() -> tuple[str, str]:
    """(commit, date) of the last commit that touched BENCH_DETAIL.json —
    the backfill for committed real-chip details that predate the
    measured_at/git_commit stamps (every fresh run writes them now, see the
    detail dict in main())."""
    try:
        out = subprocess.run(
            ["git", "log", "-1", "--format=%h %cI", "--",
             os.path.basename(DETAIL_PATH)],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        commit, _, date = out.stdout.strip().partition(" ")
        if out.returncode == 0 and commit:
            return commit, date or "unknown"
    except Exception:  # noqa: BLE001
        pass
    return "unknown", "unknown"


def _last_good_real_chip() -> dict | None:
    """The last committed real-chip BENCH_DETAIL.json, if any — the
    provenance block the fallback path attaches so a wedged tunnel at
    snapshot time can no longer erase the round's real-chip evidence."""
    try:
        with open(DETAIL_PATH) as f:
            detail = json.load(f)
    except (OSError, ValueError):
        return None
    if not _is_real_chip_detail(detail):
        return None
    primary = detail.get("decode_bf16") or {}
    if not primary.get("decode_tps"):
        return None
    if "measured_at" not in detail or "git_commit" not in detail:
        # detail predates the provenance stamps: the commit that landed the
        # file is the best-available measurement provenance
        commit, date = _detail_file_provenance()
        detail.setdefault("git_commit", commit)
        detail.setdefault("measured_at", date)
    return {
        "decode_tps": primary["decode_tps"],
        "ttft_ms": primary.get("ttft_ms"),
        "measured_at": detail["measured_at"],
        "git_commit": detail["git_commit"],
        "device": detail.get("device"),
        "best_config_tps": max(
            (v.get("decode_tps", 0.0) for v in detail.values()
             if isinstance(v, dict) and v.get("decode_tps")),
            default=primary["decode_tps"],
        ),
        "source": "BENCH_DETAIL.json (committed last-good real-chip run)",
    }


CPU_FALLBACK_MODEL = dict(
    model_type="llama",
    vocab_size=4096,
    hidden_size=512,
    intermediate_size=1408,
    num_hidden_layers=8,
    num_attention_heads=8,
    num_key_value_heads=4,
    tie_word_embeddings=True,
)


def param_count(cfg: dict) -> int:
    """Decode-path parameter count (embed excluded when tied — the head
    matmul reads it, so count it once)."""
    h, i, L, v = (
        cfg["hidden_size"],
        cfg["intermediate_size"],
        cfg["num_hidden_layers"],
        cfg["vocab_size"],
    )
    hd = cfg.get("head_dim") or h // cfg["num_attention_heads"]
    nq, nkv = cfg["num_attention_heads"], cfg["num_key_value_heads"]
    attn = h * nq * hd + 2 * h * nkv * hd + nq * hd * h
    mlp = 3 * h * i
    return L * (attn + mlp) + v * h


def hbm_bytes_per_token(cfg: dict, *, weight_bits: int, kv_dtype: str,
                        batch: int, context: int) -> dict:
    """Analytic HBM bytes read per decoded token at a stated serving point.

    Decode re-reads every decoder weight once per step (amortized over the
    batch's slots — the scheduler's live gauge divides the same way) and
    the full KV history once per step per sequence. Weight side: 4-bit
    packed is 0.5 B/param plus a bf16 scale+bias pair per quantization
    group; bf16 is 2 B/param. KV side: a bf16 row-head is 2D bytes, an
    int8 row-head is D codes + one f32 scale (cache.quantize_kv_rows).
    These are the ``weight_bytes_per_token`` / ``kv_bytes_per_token``
    gauges the quant phases record — the denominator of the
    memory-hierarchy acceptance math, independent of backend noise."""
    n = param_count(cfg)
    if weight_bits == 4:
        gs = (cfg.get("quantization") or {}).get("group_size", 64)
        wbytes = n * (0.5 + 4.0 / gs)
    else:
        wbytes = n * 2.0
    L = cfg["num_hidden_layers"]
    hkv = cfg["num_key_value_heads"]
    d = cfg.get("head_dim") or cfg["hidden_size"] // cfg["num_attention_heads"]
    row = (d + 4) if kv_dtype == "int8" else 2 * d
    return dict(
        weight_bytes_per_token=int(wbytes / batch),
        kv_bytes_per_token=int(context * L * 2 * hkv * row),
        weight_bits=weight_bits, kv_dtype=kv_dtype,
        batch=batch, context=context,
    )


def measure_decode(gen, prompt, label: str) -> dict:
    t0 = time.perf_counter()
    for i, _ in enumerate(gen.generate_step(prompt, max_tokens=4)):
        if i == 0:
            log(f"[{label}] warmup TTFT (incl. compiles) {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    first = None
    n = 0
    for _tok, _ in gen.generate_step(prompt, max_tokens=DECODE_TOKENS):
        if first is None:
            first = time.perf_counter()
        n += 1
    end = time.perf_counter()
    ttft = first - t0
    decode_tps = (n - 1) / (end - first)
    res = dict(
        label=label,
        decode_tps=round(decode_tps, 2),
        prompt_tps=round(len(prompt) / ttft, 1),
        ttft_ms=round(ttft * 1000.0, 1),
        tokens=n,
    )
    log(f"[{label}] decode={decode_tps:.2f} tok/s prompt={res['prompt_tps']} tok/s TTFT={res['ttft_ms']} ms")
    return res


def measure_cb(model, params, prompt, label: str, slots: int = 4) -> dict:
    """Aggregate continuous-batching throughput: ``slots`` concurrent
    requests interleaved in one fused engine on the one chip. Decode is
    weight-bandwidth-bound at batch 1, so slots amortize the weight stream
    and aggregate tok/s is the serving metric that matters (the reference
    serializes requests entirely — its aggregate equals its single-stream)."""
    import threading

    import jax.numpy as jnp

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    eng = PipelineEngine(
        model, params, make_mesh(pp=1), microbatches=slots,
        max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
    )
    batcher = ContinuousBatcher(eng, decode_block=8)  # the serving default
    try:
        t0 = time.perf_counter()
        for _ in batcher.generate_step(prompt, max_tokens=4):
            pass
        log(f"[{label}] warmup (incl. compiles) {time.perf_counter() - t0:.1f}s")

        done = [0] * slots

        def run(i):
            for _ in batcher.generate_step(prompt, max_tokens=DECODE_TOKENS):
                done[i] += 1

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(slots)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
    finally:
        batcher.close()
    total = sum(done)
    res = dict(
        label=label, slots=slots, aggregate_tps=round(total / dt, 2),
        per_stream_tps=round(total / dt / slots, 2), tokens=total,
        wall_s=round(dt, 1),
    )
    log(f"[{label}] slots={slots} aggregate={res['aggregate_tps']} tok/s "
        f"({res['per_stream_tps']} tok/s/stream)")
    return res


def measure_trace_overhead(model, params, label: str, slots: int = 8) -> dict:
    """Tracing cost contract (the other half of mstcheck MST112): the same
    8-slot continuous-batching load under ``--trace off``, ``sample``, and
    ``on``. Off-mode instrumentation is one attribute load and an
    ``is None`` branch per site, so its aggregate tok/s must sit inside
    run-to-run noise of a baseline off-mode run; sample/on quantify what a
    traced request actually pays. There is no uninstrumented build to
    compare against (the spans are always compiled in), so the baseline IS
    a second off-mode run — it measures the noise floor the off/baseline
    ratio is held to. Reports aggregate tok/s and p50 inter-token latency
    per mode."""
    import threading

    import jax.numpy as jnp

    from mlx_sharding_tpu import tracing
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    eng = PipelineEngine(
        model, params, make_mesh(pp=1), microbatches=slots,
        max_seq=256, cache_dtype=jnp.bfloat16, prefill_chunk=32,
    )
    batcher = ContinuousBatcher(eng, decode_block=8)
    prompt = list(range(2, 34))
    tokens = 48

    def run_mode() -> dict:
        done = [0] * slots
        gaps: list[float] = []
        gap_lock = threading.Lock()

        def run(i):
            mine = []
            last = time.perf_counter()
            for _ in batcher.generate_step(prompt, max_tokens=tokens):
                now = time.perf_counter()
                if done[i] > 0:
                    mine.append(now - last)
                last = now
                done[i] += 1
            with gap_lock:
                gaps.extend(mine)

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(slots)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        total = sum(done)
        gaps.sort()
        p50 = gaps[len(gaps) // 2] if gaps else 0.0
        # tokens surface in decode_block bursts, so p50 is the intra-block
        # gap (~0) and p90 the block boundary — report both
        p90 = gaps[int(len(gaps) * 0.9)] if gaps else 0.0
        return dict(
            aggregate_tps=round(total / dt, 2),
            itl_p50_ms=round(p50 * 1e3, 3),
            itl_p90_ms=round(p90 * 1e3, 3), tokens=total,
        )

    res: dict = dict(label=label, slots=slots)
    try:
        # two warm-up passes: the first compiles the prefill/decode graphs,
        # the second the slot-reuse sampling variant
        for _ in range(2):
            for _ in batcher.generate_step(prompt, max_tokens=4):
                pass
        for name, mode in (("baseline", "off"), ("off", "off"),
                           ("sample", "sample"), ("on", "on")):
            tracing.configure(mode, buffer=64, sample_n=4)
            res[name] = run_mode()
            log(f"[{label}] {name} (--trace {mode}): "
                f"{res[name]['aggregate_tps']} tok/s, "
                f"p50 ITL {res[name]['itl_p50_ms']} ms")
    finally:
        tracing.configure("off")
        batcher.close()
    base = res["baseline"]["aggregate_tps"]
    off = res["off"]["aggregate_tps"]
    res["off_vs_baseline"] = round(off / base, 4) if base else None
    # CPU smoke is jittery; 10% sits well above the off-mode cost (a None
    # check per site) and well below any real per-token serialization leak
    res["off_within_noise"] = bool(base) and abs(off / base - 1.0) <= 0.10
    if not res["off_within_noise"]:
        log(f"[{label}] WARNING: --trace off diverged from its own "
            f"baseline ({off} vs {base} tok/s) — off-mode tracing is "
            "supposed to be free; see mstcheck MST112")
    return res


def synth_packed_deepseek(model, key):
    """DeepSeek params in load_model(keep_quantized=True)'s exact layout,
    generated DIRECTLY in packed form on the default device — no dense
    tensor of the full model ever exists (the ~16B model is ~31 GB bf16,
    which fits neither the chip nor a sane transfer through the tunnel;
    packed it is ~10 GB). Weight VALUES are random (throughput is
    value-independent); what matters is byte-exact layout parity: packed
    {q, scales, biases} triples in MLX (out, in/8)/(out, in/64)
    orientation for every projection, with kv_b_proj and the MoE router
    kept dense exactly as packed_keep_dense_re does in compressed-MLA
    mode, and the embedding/head packed as (V, H)."""
    import jax
    import jax.numpy as jnp

    cfg = model.config
    keys = iter(jax.random.split(key, 256))
    gs, bits = model._quant_args()  # stay in lockstep with cfg.quantization
    per_word = 32 // bits

    def packed(in_dim, out_dim, lead=()):
        kq, ks, kb = jax.random.split(next(keys), 3)
        return {
            "q": jax.random.bits(
                kq, (*lead, out_dim, in_dim // per_word), jnp.uint32
            ),
            # fp16, matching the checkpoint residency keep_quantized keeps
            # (fp32 scales would add ~11% to the bytes streamed per token)
            "scales": jax.random.uniform(
                ks, (*lead, out_dim, in_dim // gs), jnp.float16, 2e-3, 8e-3
            ),
            "biases": jax.random.uniform(
                kb, (*lead, out_dim, in_dim // gs), jnp.float16, -3e-2, 0.0
            ),
        }

    def dense(in_dim, out_dim, lead=(), scale=None):
        if scale is None:
            scale = in_dim ** -0.5
        return (
            jax.random.normal(
                next(keys), (*lead, in_dim, out_dim), jnp.float32
            ) * scale
        ).astype(jnp.bfloat16)

    hd, heads = cfg.hidden_size, cfg.num_attention_heads
    nope, rope_d, v_d = (
        cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim,
    )
    rank = cfg.kv_lora_rank

    def attn(L):
        return {
            "input_norm": jnp.ones((L, hd), jnp.bfloat16),
            "post_norm": jnp.ones((L, hd), jnp.bfloat16),
            "kv_a_proj": packed(hd, rank + rope_d, (L,)),
            "kv_a_norm": jnp.ones((L, rank), jnp.bfloat16),
            # dense: consumed as a raw tensor by the absorbed compressed-MLA
            # einsums (models/deepseek_v2.py packed_keep_dense_re)
            "kv_b_proj": dense(rank, heads * (nope + v_d), (L,)),
            "o_proj": packed(heads * v_d, hd, (L,)),
            "q_proj": packed(hd, heads * (nope + rope_d), (L,)),
        }

    n_dense = cfg.first_k_dense_replace
    n_moe = cfg.num_hidden_layers - n_dense
    e, mi = cfg.n_routed_experts, cfg.moe_intermediate_size
    si = mi * (cfg.n_shared_experts or 1)
    layers = {
        "dense": {
            **attn(n_dense),
            "gate_proj": packed(hd, cfg.intermediate_size, (n_dense,)),
            "up_proj": packed(hd, cfg.intermediate_size, (n_dense,)),
            "down_proj": packed(cfg.intermediate_size, hd, (n_dense,)),
        },
        "moe": {
            **attn(n_moe),
            "router": dense(hd, e, (n_moe,)),  # dense: fp32 routing einsum
            "w_gate": packed(hd, mi, (n_moe, e)),
            "w_up": packed(hd, mi, (n_moe, e)),
            "w_down": packed(mi, hd, (n_moe, e)),
            "shared_gate": packed(hd, si, (n_moe,)),
            "shared_up": packed(hd, si, (n_moe,)),
            "shared_down": packed(si, hd, (n_moe,)),
        },
    }
    return {
        "layers": layers,
        "embed": {"weight": packed(hd, cfg.vocab_size)},
        "final_norm": {"weight": jnp.ones((hd,), jnp.bfloat16)},
        "lm_head": {"weight": packed(hd, cfg.vocab_size)},
    }


def measure_cb_prefix(model, params, label: str) -> dict:
    """Prefix-cache value measurement (VERDICT r4 weak #6): requests share a
    512-token system prompt; after the first registers its pages, later
    admissions map them read-only and prefill only the suffix. Reports the
    hit rate and the cold-vs-warm TTFT delta at identical prompt lengths —
    the delta's existence is the feature's value; its size scales with the
    shared head (here 4 of 5 prefill chunks skipped)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    eng = PipelineEngine(
        model, params, make_mesh(pp=1), microbatches=2,
        max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
        pool_pages=24, page_size=128,
    )
    batcher = ContinuousBatcher(eng, decode_block=8, prefix_cache=True)
    try:
        vocab = model.config.vocab_size

        def head(seed: int) -> list:
            rng = np.random.default_rng(seed)
            return [int(x) for x in rng.integers(1, vocab - 64, 512)]

        def ttft_ms(prefix: list, suffix_tok: int) -> float:
            t0 = time.perf_counter()
            first = None
            for _tok, _ in batcher.generate_step(
                prefix + [suffix_tok], max_tokens=16
            ):
                if first is None:
                    first = time.perf_counter() - t0
            return first * 1e3

        # warmup at the MEASURED shape with a head the measurement never
        # reuses: compiles + first-request one-time costs land here, so
        # cold-vs-warm below isolates the structural chunk-skip delta
        t0 = time.perf_counter()
        ttft_ms(head(99), vocab - 2)
        log(f"[{label}] warmup (incl. compiles) {time.perf_counter() - t0:.1f}s")

        # cold: distinct 512-token heads — every chunk prefills (median of 3)
        colds = sorted(ttft_ms(head(i), vocab - 2) for i in range(3))
        # warm: a shared head registered once, then hit (median of 3)
        shared = head(7)
        ttft_ms(shared, vocab - 3)  # registers the shared head's 4 pages
        warms = sorted(ttft_ms(shared, vocab - 4 - i) for i in range(3))
        q, h, reused, _, _ = batcher.prefix_stats()
    finally:
        batcher.close()
    cold, warm = colds[1], warms[1]
    res = dict(
        label=label, ttft_cold_ms=round(cold, 1),
        ttft_warm_ms=round(warm, 1),
        ttft_speedup=round(cold / max(warm, 1e-6), 2),
        prefix_queries=q, prefix_hits=h, tokens_reused=reused,
    )
    log(f"[{label}] TTFT cold={res['ttft_cold_ms']}ms "
        f"warm={res['ttft_warm_ms']}ms ({res['ttft_speedup']}x) "
        f"hits={h}/{q} reused={reused} tokens")
    return res


def measure_prefix_reuse_ttft(model, params, label: str) -> dict:
    """Content-addressed prefix store (PrefixStore) under a system-prompt-
    heavy arrival mix: 3 hot 3-page prefixes x 12 continuations vs 12
    all-unique prompts of the same shape, A/B store on/off. Reports p50/p99
    TTFT and prefill tokens-executed per cohort (store accounting: prompt
    tokens minus tokens served from registered pages) — the hot cohort's
    executed count dropping to ~one prefill per unique prefix is the
    feature; the TTFT delta scales with chip speed. Two more legs:
    zero-dropped-streams under fault injection at cache.prefix_lookup
    (every probe raises, every stream must still finish off the miss
    path), and the capacity composition — max live one-fresh-page sessions
    at fixed pool bytes, bf16 bare vs int8 + cold-spill + shared-prefix
    COW (the frontier composition)."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.prefix_store import PrefixStore
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from mlx_sharding_tpu.testing import faults

    vocab = model.config.vocab_size
    page = 128
    rng = np.random.default_rng(23)

    def toks(n: int) -> list:
        return [int(x) for x in rng.integers(1, vocab - 64, n)]

    hot_heads = [toks(3 * page) for _ in range(3)]
    suffixes = [toks(page // 2) for _ in range(12)]
    hot_mix = [hot_heads[i % 3] + suffixes[i] for i in range(12)]
    uniq_mix = [toks(3 * page) + suffixes[i] for i in range(12)]

    def run_mix(prompts, store) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=2,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=24, page_size=page,
        )
        kw = dict(prefix_store=store) if store is not None else {}
        batcher = ContinuousBatcher(eng, decode_block=8, **kw)
        ttfts, dropped = [], 0
        try:
            # warmup: 1-page prompt (below the store's digest floor) so
            # compiles land outside the measurement without touching stats
            for _ in batcher.generate_step(toks(page), max_tokens=8):
                pass
            for p in prompts:
                t0 = time.perf_counter()
                first = None
                for _tok, _ in batcher.generate_step(p, max_tokens=16):
                    if first is None:
                        first = time.perf_counter() - t0
                if first is None:
                    dropped += 1
                else:
                    ttfts.append(first * 1e3)
        finally:
            batcher.close()
        ttfts.sort()
        total = sum(len(p) for p in prompts)
        s = store.stats() if store is not None else {}
        return dict(
            ttft_p50_ms=round(ttfts[len(ttfts) // 2], 1) if ttfts else None,
            ttft_p99_ms=round(ttfts[-1], 1) if ttfts else None,
            prompt_tokens=total,
            prefill_tokens_executed=total - int(s.get("tokens_reused", 0)),
            tokens_reused=int(s.get("tokens_reused", 0)),
            hits=int(s.get("hits", 0)), misses=int(s.get("misses", 0)),
            inserts=int(s.get("inserts", 0)),
            lookup_faults=int(s.get("lookup_faults", 0)),
            dropped_streams=dropped,
        )

    def run_frontier(kv_dtype, pool_pages: int, composed: bool) -> dict:
        # 16 sessions over ONE shared 1-page head: bare bf16 reserves 2
        # pages each; the composed config (int8 pages + cold-slot spill +
        # store COW) maps the head read-only and parks idle slots, so live
        # climbs toward the whole session set at no more pool bytes
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=8,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=pool_pages, page_size=page, kv_dtype=kv_dtype,
        )
        kw: dict = {}
        if composed:
            kw.update(spill_bytes=256 << 20, spill_cold_after=2,
                      kv_prefetch="on",
                      prefix_store=PrefixStore(host_bytes=256 << 20))
        batcher = ContinuousBatcher(eng, decode_block=8, **kw)
        sessions = 16
        shared = toks(page)
        prompts = [shared + toks(8) for _ in range(sessions)]
        stall = threading.Event()
        started = [0]
        lock = threading.Lock()

        def consume(p):
            gen = batcher.generate_step(p, max_tokens=page - 24)
            try:
                next(gen)  # first token: the session is live
                with lock:
                    started[0] += 1
                stall.wait()  # idle mid-stream; the cold policy's shape
            finally:
                gen.close()

        threads = [
            threading.Thread(target=consume, args=(p,), daemon=True)
            for p in prompts
        ]

        def _join_all(budget_s):
            end = time.monotonic() + budget_s
            for t in threads:
                t.join(timeout=max(0.0, end - time.monotonic()))

        try:
            for _ in batcher.generate_step(prompts[0], max_tokens=8):
                pass  # compile prefill + the 8-slot decode block
            for t in threads:
                t.start()
            peak = parked = 0
            last_gain = time.monotonic()
            deadline = last_gain + 30.0
            while time.monotonic() < deadline:
                s = batcher.spill_stats() or {}
                _, in_use, _ = batcher.page_stats()
                parked = int(s.get("parked", 0))
                if composed:
                    # resident sessions hold 1 fresh page past the shared
                    # head; parked ones hold none (pages released to host)
                    live = max(0, in_use - 1) + parked
                else:
                    live = in_use // 2  # 2 reserved pages per session
                if live > peak:
                    peak, last_gain = live, time.monotonic()
                if peak >= sessions or time.monotonic() - last_gain > 3.0:
                    break
                time.sleep(0.002)
            stall.set()
            # consumers still waiting on admission stay blocked until
            # close() feeds them the shutdown sentinel
            _join_all(5.0)
        finally:
            batcher.close()
        _join_all(30.0)
        return dict(kv_dtype=kv_dtype, pool_pages=pool_pages,
                    peak_live_sessions=peak, parked=parked,
                    sessions_started=started[0], sessions=sessions)

    res = dict(label=label)
    res["hot_store"] = run_mix(hot_mix, PrefixStore(host_bytes=256 << 20))
    res["hot_bare"] = run_mix(hot_mix, None)
    res["uniq_store"] = run_mix(uniq_mix, PrefixStore(host_bytes=256 << 20))
    res["uniq_bare"] = run_mix(uniq_mix, None)
    # fault leg: every prefix_lookup probe raises; streams degrade to the
    # miss path and must all complete — dropped_streams is the contract
    faults.arm("cache.prefix_lookup", exc=faults.FaultError)
    try:
        res["hot_store_lookup_fault"] = run_mix(
            hot_mix, PrefixStore(host_bytes=256 << 20)
        )
    finally:
        faults.disarm()
    d = model.config.head_dim
    pages_bf16 = 4
    pages_int8 = int(pages_bf16 * (2 * d) / (d + 4))
    res["frontier_bf16"] = run_frontier("bf16", pages_bf16, composed=False)
    res["frontier_composed"] = run_frontier("int8", pages_int8,
                                            composed=True)
    hs, hb = res["hot_store"], res["hot_bare"]
    log(f"[{label}] hot mix: prefill exec {hs['prefill_tokens_executed']}"
        f"/{hs['prompt_tokens']} tok (bare {hb['prefill_tokens_executed']}), "
        f"p50 TTFT {hs['ttft_p50_ms']}ms vs {hb['ttft_p50_ms']}ms, "
        f"fault-leg dropped={res['hot_store_lookup_fault']['dropped_streams']}"
        f" (faults={res['hot_store_lookup_fault']['lookup_faults']}); "
        f"frontier live {res['frontier_bf16']['peak_live_sessions']} -> "
        f"{res['frontier_composed']['peak_live_sessions']}"
        f"/{res['frontier_composed']['sessions']}")
    return res


def measure_cb_overcommit(model, params, label: str) -> dict:
    """Over-commit occupancy under MIXED traffic (VERDICT r4 weak #3: the
    uniform cb config never showed it). Four requests ask for a large
    budget (max_tokens=320 → a 3-page reservation) but their consumers
    stop after 32 tokens — the shape stop-sequence traffic has. On a
    4-page pool, reserve admission can only run them one at a time;
    over-commit admits on current need (1 page) and runs all four
    interleaved. Reports batch wall-clock under both modes."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    vocab = model.config.vocab_size
    prompts = [
        [int(x) for x in np.random.default_rng(s).integers(1, vocab - 64, 64)]
        for s in range(4)
    ]

    def run(overcommit: bool) -> float:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=4,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=4, page_size=128,
        )
        batcher = ContinuousBatcher(
            eng, decode_block=8, overcommit=overcommit
        )
        try:
            for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
                pass  # compile prefill + decode block

            def consume(p):
                n = 0
                for _ in batcher.generate_step(p, max_tokens=320):
                    n += 1
                    if n >= 32:
                        break  # stop sequence matched; slot reclaimed

            threads = [
                threading.Thread(target=consume, args=(p,)) for p in prompts
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0
        finally:
            batcher.close()

    wall_reserve = run(False)
    wall_oc = run(True)
    res = dict(
        label=label, wall_reserve_s=round(wall_reserve, 2),
        wall_overcommit_s=round(wall_oc, 2),
        speedup=round(wall_reserve / max(wall_oc, 1e-9), 2),
    )
    log(f"[{label}] mixed-traffic batch: reserve={res['wall_reserve_s']}s "
        f"overcommit={res['wall_overcommit_s']}s ({res['speedup']}x)")
    return res


def measure_preempt_spill_vs_discard(model, params, label: str) -> dict:
    """KV spill A/B (ISSUE 6 tentpole): the same over-commit-pressure batch
    run with preemption-as-discard (re-prefill the victim from its folded
    prompt) and preemption-as-spill (--spill-bytes: export the victim's
    page block to host DRAM, re-import on resume). Two requests whose full
    need is over half a 4-page pool thrash each other; the spill run should
    show re-import hits and fewer re-prefilled tokens for comparable wall."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    vocab = model.config.vocab_size
    prompts = [
        [int(x) for x in np.random.default_rng(s).integers(1, vocab - 64, 64)]
        for s in range(2)
    ]

    def run(spill_bytes) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=2,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=4, page_size=128,
        )
        batcher = ContinuousBatcher(
            eng, decode_block=8, overcommit=True, spill_bytes=spill_bytes
        )
        try:
            for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
                pass  # compile prefill + decode block

            def consume(p):
                for _ in batcher.generate_step(p, max_tokens=320):
                    pass

            threads = [
                threading.Thread(target=consume, args=(p,)) for p in prompts
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            s = batcher.spill_stats() or {}
            return dict(
                wall_s=round(wall, 2),
                preemptions=s.get("preemptions", 0),
                spills=s.get("spills", 0),
                spill_hits=s.get("spill_hits", 0),
                spill_fallbacks=s.get("spill_fallbacks", 0),
                reprefill_tokens=s.get("reprefill_tokens", 0),
            )
        finally:
            batcher.close()

    discard = run(None)
    spill = run(256 << 20)
    res = dict(label=label, discard=discard, spill=spill,
               speedup=round(discard["wall_s"] / max(spill["wall_s"], 1e-9), 2))
    log(f"[{label}] discard: wall={discard['wall_s']}s "
        f"preempt={discard['preemptions']} "
        f"reprefill={discard['reprefill_tokens']} | spill: "
        f"wall={spill['wall_s']}s hits={spill['spill_hits']} "
        f"reprefill={spill['reprefill_tokens']} ({res['speedup']}x)")
    return res


def measure_replica_drain(model, params, label: str) -> dict:
    """Graceful-drain evidence (ISSUE 6): two single-stage paged batcher
    replicas, a stream live on replica 0, drain(0) mid-stream. Records how
    long the drain took, how many requests it migrated, and — the actual
    contract — that the client stream completed with zero drops."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.replicas import ReplicaSet
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    devices = jax.devices()
    if len(devices) < 2:
        return dict(label=label, skipped="needs 2 devices")
    reps = []
    for i in range(2):
        eng = PipelineEngine(
            model, params, make_mesh(pp=1, devices=devices[i : i + 1]),
            microbatches=2, max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16,
            prefill_chunk=128, pool_pages=8, page_size=128,
        )
        reps.append(ContinuousBatcher(eng, decode_block=8))
    rs = ReplicaSet(reps)
    vocab = model.config.vocab_size
    prompt = [
        int(x) for x in np.random.default_rng(9).integers(1, vocab - 64, 64)
    ]
    try:
        for _ in reps[1].generate_step(prompt[:16], max_tokens=8):
            pass  # compile the survivor's programs off the clock
        toks: list = []
        errs: list = []
        started = threading.Event()

        def consume():
            try:
                for t, _ in rs.generate_step(prompt, max_tokens=96):
                    toks.append(t)
                    started.set()
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                errs.append(repr(e)[:200])
                started.set()

        th = threading.Thread(target=consume)
        th.start()
        started.wait(120)
        t0 = time.perf_counter()
        out = rs.drain(0)
        drain_s = time.perf_counter() - t0
        th.join(timeout=120)
        res = dict(
            label=label,
            drain_s=round(drain_s, 2),
            migrated=out.get("migrated", 0),
            closed=bool(out.get("closed")),
            stream_tokens=len(toks),
            dropped_streams=len(errs) + (1 if th.is_alive() else 0),
            errors=errs,
        )
        log(f"[{label}] drain={res['drain_s']}s migrated={res['migrated']} "
            f"stream_tokens={res['stream_tokens']} "
            f"dropped={res['dropped_streams']}")
        return res
    finally:
        rs.close()


def measure_fleet_elasticity(model, params, label: str) -> dict:
    """Elastic-fleet evidence (ISSUE 7). Phase 1: skewed load (one replica
    carries a long background stream) over a 2-replica fleet — p99 queue
    wait (TTFT) under blind round-robin placement vs the ReplicaSet's
    score routing. Phase 2: a request storm while the autoscaler runs with
    an injected spawn failure (degrades to the static fleet), a killed
    dispatch on replica 0 (the request re-places), a real scale-up onto a
    spare device, and a scale-down drain once the storm ends. The contract
    throughout: zero dropped streams, autoscale events recorded."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.fleet import FleetAutoscaler
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.replicas import ReplicaSet
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from mlx_sharding_tpu.testing import faults

    devices = jax.devices()
    if len(devices) < 2:
        return dict(label=label, skipped="needs 2 devices")

    def build(i):
        # wrap so the spawned 3rd replica still lands somewhere on a
        # 2-device host (sharing a device is fine: this phase measures
        # control-plane behaviour, not per-replica throughput)
        i = i % len(devices)
        eng = PipelineEngine(
            model, params, make_mesh(pp=1, devices=devices[i : i + 1]),
            microbatches=2, max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16,
            prefill_chunk=128, pool_pages=8, page_size=128,
        )
        return ContinuousBatcher(eng, decode_block=8)

    vocab = model.config.vocab_size
    prompt = [
        int(x) for x in
        np.random.default_rng(11).integers(1, vocab - 64, 16)
    ]

    def run_jobs(dispatch, n):
        """n concurrent short streams; returns (ttfts, errors)."""
        ttfts, errs = [], []
        lock = threading.Lock()

        def one(k):
            t0 = time.perf_counter()
            try:
                first = True
                for _ in dispatch(k):
                    if first:
                        first = False
                        with lock:
                            ttfts.append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                with lock:
                    errs.append(repr(e)[:200])

        threads = [threading.Thread(target=one, args=(k,)) for k in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        return ttfts, errs

    def p99(xs):
        return round(float(np.percentile(xs, 99)), 3) if xs else None

    reps = [build(0), build(1)]
    rs = ReplicaSet(reps)
    result = dict(label=label)
    try:
        for r in reps:  # compile both replicas' programs off the clock
            for _ in r.generate_step(prompt, max_tokens=4):
                pass

        # ---- phase 1: skewed load, round-robin vs score routing --------
        def skewed(dispatch):
            bg_done = threading.Event()

            def background():
                for _ in reps[0].generate_step(prompt, max_tokens=96):
                    pass
                bg_done.set()

            bg = threading.Thread(target=background)
            bg.start()
            out = run_jobs(dispatch, n=10)
            bg.join(timeout=180)
            return out

        rr_ttfts, rr_errs = skewed(
            lambda k: reps[k % 2].generate_step(prompt, max_tokens=8)
        )
        routed_ttfts, routed_errs = skewed(
            lambda k: rs.generate_step(prompt, max_tokens=8)
        )
        result["routing"] = dict(
            round_robin_p99_wait_s=p99(rr_ttfts),
            score_routed_p99_wait_s=p99(routed_ttfts),
            affinity_hits=rs.route_affinity_hits,
            dropped_streams=len(rr_errs) + len(routed_errs),
        )

        # ---- phase 2: storm + spawn failure + kill + scale-down --------
        spawn_calls = {"n": 0}

        def factory():
            spawn_calls["n"] += 1
            return build(2)

        # min_replicas=2: a mid-storm dispatch kill needs a live peer to
        # re-place onto; scale_down_sustain_s > 0 keeps momentary lulls
        # between job waves from draining the fleet out from under the storm
        ctrl = FleetAutoscaler(
            rs, factory, min_replicas=2, max_replicas=3,
            scale_up_pressure=0.5, scale_up_sustain_s=0.0,
            scale_down_pressure=0.05, scale_down_sustain_s=0.3,
            cooldown_s=0.0, drain_deadline_s=30.0,
        )
        faults.arm("replica.spawn", exc=RuntimeError, times=1)
        faults.arm("replica.dispatch", exc=RuntimeError, times=1,
                   match={"replica": 0})
        storm = {"ttfts": [], "errs": []}
        done = threading.Event()

        def run_storm():
            t, e = run_jobs(
                lambda k: rs.generate_step(prompt, max_tokens=8), n=8
            )
            storm["ttfts"], storm["errs"] = t, e
            done.set()

        th = threading.Thread(target=run_storm)
        th.start()
        while not done.is_set():
            ctrl.tick()
            done.wait(0.05)
        th.join(timeout=180)
        for _ in range(8):  # idle ticks past the sustain window: the
            ctrl.tick()     # scale-down side of the loop drains 3 -> 2
            time.sleep(0.1)
        ev = rs.fleet_stats()["autoscale_events"]
        result["elasticity"] = dict(
            spawn_failures=ev.get("spawn_failed", 0),
            spawns=ev.get("spawn", 0),
            drains=ev.get("drain", 0),
            events=dict(ev),
            fleet_size=rs.fleet_stats()["size"],
            p99_wait_s=p99(storm["ttfts"]),
            dropped_streams=len(storm["errs"]),
            errors=storm["errs"],
        )
        result["zero_dropped_streams"] = (
            result["routing"]["dropped_streams"] == 0
            and not storm["errs"]
        )
        log(f"[{label}] rr_p99={result['routing']['round_robin_p99_wait_s']}s "
            f"routed_p99={result['routing']['score_routed_p99_wait_s']}s | "
            f"spawn_failed={result['elasticity']['spawn_failures']} "
            f"spawned={result['elasticity']['spawns']} "
            f"drained={result['elasticity']['drains']} "
            f"dropped={result['elasticity']['dropped_streams']}")
        return result
    finally:
        faults.disarm()
        rs.close()


def measure_weight_sharing(model, params, label: str) -> dict:
    """Cross-replica shared weights (ISSUE 10). A/B over an N=3 fleet:
    private mode uploads one resident tree per replica (the pre-store
    behaviour), shared mode places ONE tree and every replica aliases it
    through a WeightStore lease. Records (1) fleet-resident weight bytes
    under unique-buffer accounting — ~W shared vs N×W private is the
    headline; (2) spawn latency — full checkpoint re-placement vs
    alias-fast construction, the autoscaler's scale-out stall; (3) greedy
    parity — shared and private replicas must stream identical tokens."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh, mesh_fingerprint
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine, place_weights
    from mlx_sharding_tpu.weights import WeightKey, WeightStore

    devices = jax.devices()
    n = 3
    vocab = model.config.vocab_size
    prompt = [
        int(x) for x in
        np.random.default_rng(23).integers(1, vocab - 64, 16)
    ]
    kw = dict(max_seq=256, cache_dtype=jnp.bfloat16, prefill_chunk=16)

    def unique_bytes(engines):
        seen, total = set(), 0
        for e in engines:
            for leaf in jax.tree.leaves(
                (e.layer_params, e.vocab_parts, e.shared_params)
            ):
                if id(leaf) not in seen:
                    seen.add(id(leaf))
                    total += leaf.nbytes
        return total

    # ---- private fleet: one full placement per replica ------------------
    t_full = time.perf_counter()
    first_private = PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[:1]), **kw
    )
    spawn_full_s = time.perf_counter() - t_full
    private = [first_private] + [
        PipelineEngine(
            model, params,
            make_mesh(pp=1, devices=devices[i % len(devices):
                                            i % len(devices) + 1]),
            **kw,
        )
        for i in range(1, n)
    ]
    bytes_private = unique_bytes(private)
    want = [t for t, _ in first_private.generate_step(prompt, max_tokens=16)]

    # ---- shared fleet: one placement, N aliased replicas ----------------
    store = WeightStore()
    mesh = make_mesh(pp=1, devices=devices[:1])
    key = WeightKey(checkpoint="bench", stage_bounds=("auto", 1),
                    dtype="bfloat16", quant="tp1",
                    placement=mesh_fingerprint(mesh))
    leases, shared, alias_times = [], [], []
    for i in range(n):
        t0 = time.perf_counter()
        lease = store.acquire(
            key, lambda: place_weights(model, params, mesh)
        )
        eng = PipelineEngine(
            model, None, lease.weights.mesh, weights=lease.weights, **kw
        )
        eng.on_close(lease.release)
        if i > 0:  # i=0 pays the one real upload; the aliases are the A/B
            alias_times.append(time.perf_counter() - t0)
        leases.append(lease)
        shared.append(eng)
    bytes_shared = unique_bytes(shared)
    parity = all(
        [t for t, _ in e.generate_step(prompt, max_tokens=16)] == want
        for e in shared
    )
    for e in shared:
        e.close()
    assert store.stats()["trees"] == 0

    spawn_alias_s = float(np.mean(alias_times))
    result = dict(
        label=label,
        replicas=n,
        fleet_weight_bytes_private=int(bytes_private),
        fleet_weight_bytes_shared=int(bytes_shared),
        bytes_ratio=round(bytes_private / max(1, bytes_shared), 2),
        spawn_full_s=round(spawn_full_s, 3),
        spawn_alias_s=round(spawn_alias_s, 3),
        spawn_speedup=round(spawn_full_s / max(1e-9, spawn_alias_s), 1),
        greedy_parity=bool(parity),
    )
    log(f"[{label}] fleet bytes {bytes_private / 1e6:.1f}MB private -> "
        f"{bytes_shared / 1e6:.1f}MB shared ({result['bytes_ratio']}x) | "
        f"spawn {spawn_full_s:.3f}s full -> {spawn_alias_s:.3f}s alias "
        f"({result['spawn_speedup']}x) | parity={parity}")
    return result


def measure_disagg_prefill_decode(model, params, label: str) -> dict:
    """Disaggregated prefill/decode A/B (ISSUE 8 tentpole): the same mixed
    workload — decode-saturated slots plus long-prefill arrivals — through
    (a) a 2-replica monolithic ReplicaSet where every replica serves both
    phases, and (b) a DisaggCoordinator fronting a 1-replica prefill pool
    and a 1-replica decode pool on the same two devices. Monolithic, an
    arriving long prefill interleaves its chunks with the busy replica's
    decode ticks, so its TTFT pays the contention; disaggregated, the
    chunks run back-to-back on the prefill replica (which decode load
    never touches) and the stream hands its KV block to the decode pool
    after the first token. Records TTFT p50/p99 of the long-prefill
    arrivals and background decode tok/s under both topologies — the TTFT
    tail under decode saturation is the headline."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.disagg import DisaggCoordinator
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.replicas import ReplicaSet
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    devices = jax.devices()
    if len(devices) < 2:
        return dict(label=label, skipped="needs 2 devices")
    vocab = model.config.vocab_size
    rng = np.random.default_rng(17)
    bg_prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, 12)] for _ in range(2)
    ]
    fg_prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, 192)] for _ in range(4)
    ]

    def build(i):
        eng = PipelineEngine(
            model, params, make_mesh(pp=1, devices=devices[i : i + 1]),
            microbatches=2, max_seq=512, cache_dtype=jnp.bfloat16,
            prefill_chunk=16, pool_pages=24, page_size=32,
        )
        return ContinuousBatcher(eng, decode_block=4)

    def run(kind: str) -> dict:
        reps = [build(0), build(1)]
        if kind == "monolithic":
            front = ReplicaSet(reps)
        else:
            front = DisaggCoordinator(
                ReplicaSet(reps[:1], role="prefill"),
                ReplicaSet(reps[1:], role="decode"),
            )
        try:
            for r in reps:  # compile prefill + decode off the clock
                for _ in r.generate_step(fg_prompts[0][:32], max_tokens=4):
                    pass
            bg_tokens = [0] * len(bg_prompts)
            bg_started = [threading.Event() for _ in bg_prompts]

            def background(i):
                for _ in front.generate_step(bg_prompts[i], max_tokens=96):
                    bg_tokens[i] += 1
                    bg_started[i].set()

            bgs = [
                threading.Thread(target=background, args=(i,))
                for i in range(len(bg_prompts))
            ]
            t0 = time.perf_counter()
            for t in bgs:
                t.start()
            for ev in bg_started:  # decode saturation established
                ev.wait(120)

            ttfts: list = []
            errs: list = []
            lock = threading.Lock()

            def foreground(p):
                s = time.perf_counter()
                try:
                    first = None
                    for _ in front.generate_step(p, max_tokens=8):
                        if first is None:
                            first = time.perf_counter() - s
                    with lock:
                        ttfts.append(first)
                except Exception as e:  # noqa: BLE001 — recorded, not raised
                    with lock:
                        errs.append(repr(e)[:200])

            fgs = [
                threading.Thread(target=foreground, args=(p,))
                for p in fg_prompts
            ]
            for t in fgs:
                t.start()
            for t in fgs + bgs:
                t.join(timeout=240)
            wall = time.perf_counter() - t0
            out = dict(
                ttft_p50_ms=round(
                    float(np.percentile(ttfts, 50)) * 1e3, 1
                ) if ttfts else None,
                ttft_p99_ms=round(
                    float(np.percentile(ttfts, 99)) * 1e3, 1
                ) if ttfts else None,
                bg_decode_tok_s=round(sum(bg_tokens) / max(wall, 1e-9), 1),
                dropped_streams=len(errs) + sum(
                    1 for t in fgs + bgs if t.is_alive()
                ),
                errors=errs,
            )
            if kind == "disagg":
                h = front.handoff_stats()
                out["handoffs"] = h["handoffs"]
                out["handoff_ms_p50"] = (
                    round(h["ms_p50"], 3) if h["ms_p50"] is not None else None
                )
                out["fallbacks"] = dict(h["fallbacks"])
            return out
        finally:
            front.close()

    mono = run("monolithic")
    dis = run("disagg")
    res = dict(label=label, monolithic=mono, disagg=dis)
    if mono.get("ttft_p99_ms") and dis.get("ttft_p99_ms"):
        res["ttft_p99_speedup"] = round(
            mono["ttft_p99_ms"] / max(dis["ttft_p99_ms"], 1e-9), 2
        )
    log(f"[{label}] long-prefill TTFT p99 under decode saturation: "
        f"monolithic={mono.get('ttft_p99_ms')}ms "
        f"disagg={dis.get('ttft_p99_ms')}ms "
        f"({res.get('ttft_p99_speedup')}x); handoffs={dis.get('handoffs')} "
        f"dropped={mono['dropped_streams'] + dis['dropped_streams']}")
    return res


def measure_pod_fleet(model, params, label: str) -> dict:
    """Pod-scale multihost smoke (ISSUE 15 tentpole) over the loopback
    fabric: two simulated hosts, each holding ONE packed weight tree that
    both of its local engines alias (the pod weight bytes are
    N_hosts x W, not N_replicas x W), a cross-host prefill→decode handoff
    stream (serialized KVPageBlock over the pod wire, tokens relayed
    back), and a host-kill storm — the remote host goes silent mid-relay
    and every stream must drain onto the origin with zero drops. Records
    the aliased/naive weight-byte ratio, handoff first-token latency
    p50/p99, relayed decode tok/s, and the storm's completion count."""
    import threading
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.disagg import DisaggCoordinator
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import (
        PipelineEngine,
        place_weights,
    )
    from mlx_sharding_tpu.pod import LoopbackHub, PodFleet
    from mlx_sharding_tpu.replicas import ReplicaSet
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from mlx_sharding_tpu.weights import WeightKey, WeightStore

    devices = jax.devices()
    if len(devices) < 2:
        return dict(label=label, skipped="needs 2 devices")
    vocab = model.config.vocab_size
    rng = np.random.default_rng(23)
    prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, 16)] for _ in range(4)
    ]
    kw = dict(max_tokens=24)

    # one packed tree per "host", aliased by both of that host's engines
    stores = {0: WeightStore(), 1: WeightStore()}
    leases = []

    def aliased_batcher(host):
        dev = devices[host:host + 1]
        mesh = make_mesh(pp=1, devices=dev)
        key = WeightKey(checkpoint="bench-pod", stage_bounds=(("auto", 1),),
                       dtype="bfloat16", quant="none",
                       placement=f"pod-host-{host}")
        lease = stores[host].acquire(
            key, lambda: place_weights(model, params, mesh))
        leases.append(lease)
        eng = PipelineEngine(
            model, None, lease.weights.mesh, weights=lease.weights,
            microbatches=2, max_seq=256, cache_dtype=jnp.bfloat16,
            prefill_chunk=16, pool_pages=24, page_size=16,
        )
        eng.on_close(lease.release)
        return ContinuousBatcher(eng, decode_block=4)

    co = DisaggCoordinator(
        ReplicaSet([aliased_batcher(0)], role="prefill"),
        ReplicaSet([aliased_batcher(0)], role="decode"),
    )
    b1 = aliased_batcher(1)
    _idle = aliased_batcher(1)  # second local ref proves the aliasing

    weight_meta = {}
    for host, store in stores.items():
        st = store.stats()
        weight_meta[f"host{host}"] = dict(
            trees=st["trees"], refs=st["refs"], bytes=st["bytes"])
    pod_bytes = sum(m["bytes"] for m in weight_meta.values())
    naive_bytes = sum(m["bytes"] * m["refs"] for m in weight_meta.values())

    def run_pod(kill_after_tokens=None):
        """Serve every prompt through the pod; optionally go silent after
        N relayed tokens (the host-death drain)."""
        hub = LoopbackHub()
        f0 = PodFleet(0, hub.register(0), co)
        f1 = PodFleet(1, hub.register(1), b1)
        f0.tick()
        f1.tick()
        f0.start()  # keep heartbeats fresh while the streams run
        f1.start()
        f0.handoff.local_pressure = lambda: 1.0
        if kill_after_tokens is not None:
            f0.handoff.relay_timeout_s = 1.0
            orig = hub._handlers[0]
            relayed = [0]

            def silent(src, kind, payload):
                if kind == "pod.tok":
                    relayed[0] += 1
                    if relayed[0] > kill_after_tokens:
                        return
                elif kind == "pod.end":
                    return
                orig(src, kind, payload)

            hub._handlers[0] = silent
        done = []
        errors = []

        def worker(p):
            try:
                done.append(len([t for t, _ in co.generate_step(p, **kw)]))
            except Exception as e:  # noqa: BLE001 — a drop, counted
                errors.append(repr(e)[:120])

        t0 = _time.perf_counter()
        threads = [threading.Thread(target=worker, args=(p,))
                   for p in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = _time.perf_counter() - t0
        stats = f0.handoff.stats()
        f0.close(close_local=False)
        f1.close(close_local=False)
        co.pod = None
        return done, errors, dt, stats

    try:
        # steady state: every decode leg relayed from the remote host
        done, errors, dt, h = run_pod()
        steady = dict(
            completed=len(done), dropped=len(errors),
            shipped=h["shipped"], bytes_shipped=h["bytes_shipped"],
            relayed_tokens=h["relayed_tokens"],
            first_token_ms_p50=round(h["ms_p50"], 2) if h["ms_p50"] else None,
            first_token_ms_p99=round(h["ms_p99"], 2) if h["ms_p99"] else None,
            relayed_tps=round(h["relayed_tokens"] / max(dt, 1e-9), 2),
            fallbacks=h["fallbacks"],
        )
        # host-kill storm: remote goes silent after 2 relayed tokens per
        # stream — every stream must drain locally, token-exact, no drops
        done, errors, dt, h = run_pod(kill_after_tokens=2)
        storm = dict(
            completed=len(done), dropped=len(errors),
            fallbacks=h["fallbacks"], wall_s=round(dt, 2),
        )
    finally:
        co.close()
        b1.close()
        _idle.close()

    res = dict(
        label=label, weights=weight_meta,
        pod_weight_bytes=pod_bytes, naive_weight_bytes=naive_bytes,
        weight_bytes_saved_frac=round(1 - pod_bytes / max(naive_bytes, 1), 3),
        steady=steady, kill_storm=storm,
    )
    log(f"[{label}] pod weights {pod_bytes / 2**20:.1f}MiB aliased vs "
        f"{naive_bytes / 2**20:.1f}MiB naive; handoff first-token "
        f"p50={steady['first_token_ms_p50']}ms "
        f"p99={steady['first_token_ms_p99']}ms "
        f"relayed {steady['relayed_tps']} tok/s; kill storm "
        f"{storm['completed']}/{len(prompts)} drained, "
        f"dropped={storm['dropped']}")
    return res


def measure_pod_prefix_federation(model, params, label: str) -> dict:
    """Pod-federated prefix store over a 2-host loopback fabric: each hot
    system prompt is prefilled exactly once POD-WIDE. Host A serves the
    hot heads (demoting each prefix to its host tier), inventories gossip
    on the heartbeat, then host B serves the continuation mix — its local
    miss consults the pod view and pulls the owner's blob over the fabric
    (one counted fetch per unique prefix), importing it through the normal
    store path so only suffix tokens prefill. Reports host-B p50/p99 TTFT,
    fetch count/bytes, and tokens reused vs executed. A second leg arms
    the ``pod.prefix_fetch`` fault site: every consult fails, every stream
    must still complete off the plain-prefill path — zero drops."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.pod import LoopbackHub, PodFleet
    from mlx_sharding_tpu.prefix_store import PrefixStore
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from mlx_sharding_tpu.testing import faults

    devices = jax.devices()
    if len(devices) < 2:
        return dict(label=label, skipped="needs 2 devices")
    page = 128
    vocab = model.config.vocab_size
    rng = np.random.default_rng(29)

    def toks(n: int) -> list:
        return [int(x) for x in rng.integers(1, vocab - 64, n)]

    hot_heads = [toks(2 * page) for _ in range(2)]
    suffixes = [toks(page // 2) for _ in range(8)]

    def mk_host(i: int):
        eng = PipelineEngine(
            model, params, make_mesh(pp=1, devices=devices[i:i + 1]),
            microbatches=2, max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16,
            prefill_chunk=128, pool_pages=24, page_size=page,
        )
        store = PrefixStore()
        return ContinuousBatcher(eng, decode_block=8,
                                 prefix_store=store), store

    b_a, s_a = mk_host(0)
    b_b, s_b = mk_host(1)
    hub = LoopbackHub()
    f_a = PodFleet(0, hub.register(0), b_a, prefix_store=s_a)
    f_b = PodFleet(1, hub.register(1), b_b, prefix_store=s_b)
    try:
        # one prefill per unique prefix pod-wide: the hot heads run ONLY
        # on host A; stream completion demotes each prefix into A's host
        # tier, whose inventory rides the next heartbeat
        for head in hot_heads:
            for _ in b_a.generate_step(head + toks(8), max_tokens=8):
                pass
        f_a.tick()
        f_b.tick()
        a_stats = s_a.stats()
        ttfts = []
        dropped = 0
        for i, suf in enumerate(suffixes):
            prompt = hot_heads[i % len(hot_heads)] + suf
            t0 = _time.perf_counter()
            first = None
            for _tok, _ in b_b.generate_step(prompt, max_tokens=16):
                if first is None:
                    first = _time.perf_counter() - t0
            if first is None:
                dropped += 1
            else:
                ttfts.append(first * 1e3)
        ttfts.sort()
        fed = f_b.prefix.stats()
        st_b = s_b.stats()
        total_b = sum(len(hot_heads[i % len(hot_heads)]) + len(s)
                      for i, s in enumerate(suffixes))
        steady = dict(
            completed=len(ttfts), dropped_streams=dropped,
            ttft_p50_ms=round(ttfts[len(ttfts) // 2], 1) if ttfts else None,
            ttft_p99_ms=round(ttfts[-1], 1) if ttfts else None,
            fetches=fed["fetches"], fetch_bytes=fed["fetch_bytes"],
            fetch_ms_p50=fed["fetch_ms_p50"], fallbacks=fed["fallbacks"],
            prompt_tokens=total_b,
            tokens_reused=int(st_b.get("tokens_reused", 0)),
            prefill_tokens_executed=(
                total_b - int(st_b.get("tokens_reused", 0))),
            host_a_demotions=int(a_stats.get("demotions", 0)),
        )
        # fault leg: a fresh head lives only on A; every consult from B
        # faults at pod.prefix_fetch and must degrade to plain prefill
        extra = toks(2 * page)
        for _ in b_a.generate_step(extra + toks(8), max_tokens=8):
            pass
        f_a.tick()
        f_b.tick()
        faults.arm("pod.prefix_fetch", exc=faults.FaultError, times=8)
        try:
            n = 0
            for _tok, _ in b_b.generate_step(extra + toks(16),
                                             max_tokens=8):
                n += 1
        finally:
            faults.disarm()
        fed2 = f_b.prefix.stats()
        fault_leg = dict(
            tokens=n, dropped_streams=int(n == 0),
            fetch_faults=int(fed2["fallbacks"].get("fetch_fault", 0)),
        )
    finally:
        faults.disarm()
        f_a.close(close_local=False)
        f_b.close(close_local=False)
        b_a.close()
        b_b.close()
    res = dict(label=label, steady=steady, fault_leg=fault_leg)
    log(f"[{label}] pod prefix federation: {steady['fetches']} fetch(es) "
        f"{steady['fetch_bytes']}B for {len(hot_heads)} hot prefix(es); "
        f"host-B TTFT p50={steady['ttft_p50_ms']}ms "
        f"p99={steady['ttft_p99_ms']}ms reused={steady['tokens_reused']} "
        f"tok; fault leg: {fault_leg['fetch_faults']} fault(s), "
        f"dropped={fault_leg['dropped_streams']}")
    return res


def measure_kv_share_capacity(model, params, label: str) -> dict:
    """KVSharer layer-wise KV sharing (arXiv:2410.18517) at fixed pool
    bytes: calibrate a share map on the fly (most-dissimilar layer pairs
    merged), then drive the same idle-session mix as the capacity
    frontier through three pools holding (no more than) the SAME bytes —
    unshared bf16, shared bf16 (L/G x the pages), and shared int8 +
    cold-spill (the composed frontier). Peak live sessions is read from
    public gauges only; the shared pool's byte budget is verified
    directly off the engine's pool leaves."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.cli.kv_share_calibrate import calibrate_model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    cfg = model.config
    n_layers = cfg.num_hidden_layers
    d = cfg.head_dim
    vocab = cfg.vocab_size
    rng = np.random.default_rng(31)
    calib = [
        [int(x) for x in rng.integers(1, vocab - 64, 24)] for _ in range(3)
    ]
    share = calibrate_model(model, params, calib,
                            num_share=max(1, n_layers // 2),
                            cache_dtype=jnp.bfloat16)
    groups = share.num_groups
    page_size = 128
    pages_base = 4
    pages_shared = pages_base * n_layers // groups
    pages_int8_shared = int(pages_shared * (2 * d) / (d + 4))
    sessions = 12
    prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, 8)]
        for _ in range(sessions)
    ]
    spill_kw = dict(spill_bytes=256 << 20, spill_cold_after=2,
                    kv_prefetch="on")

    def _join_all(threads, budget_s):
        end = time.monotonic() + budget_s
        for t in threads:
            t.join(timeout=max(0.0, end - time.monotonic()))

    def run(kv_dtype: str, pool_pages: int, share_map, spill: bool) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=8,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=pool_pages, page_size=page_size, kv_dtype=kv_dtype,
            kv_share_map=share_map,
        )
        batcher = ContinuousBatcher(
            eng, decode_block=8, **(spill_kw if spill else {})
        )
        stall = threading.Event()

        def consume(p):
            gen = batcher.generate_step(p, max_tokens=page_size - 16)
            try:
                next(gen)
                stall.wait()
            finally:
                gen.close()

        threads = [
            threading.Thread(target=consume, args=(p,), daemon=True)
            for p in prompts
        ]
        try:
            for _ in batcher.generate_step(prompts[0], max_tokens=8):
                pass  # compile
            for t in threads:
                t.start()
            peak = 0
            last_gain = time.monotonic()
            deadline = last_gain + 30.0
            while time.monotonic() < deadline:
                st = batcher.spill_stats() or {}
                _, in_use, _ = batcher.page_stats()
                live = in_use + int(st.get("parked", 0))
                if live > peak:
                    peak, last_gain = live, time.monotonic()
                if peak >= sessions or time.monotonic() - last_gain > 3.0:
                    break
                time.sleep(0.002)
            pool_bytes = sum(
                leaf.nbytes for leaf in
                jax.tree.leaves((batcher.cache.k, batcher.cache.v))
            )
            ss = eng.kv_share_stats()
            stall.set()
            _join_all(threads, 5.0)
        finally:
            batcher.close()
        _join_all(threads, 30.0)
        return dict(
            kv_dtype=kv_dtype, pool_pages=pool_pages,
            pool_bytes=int(pool_bytes), peak_live_sessions=peak,
            share_groups=(ss or {}).get("groups"),
            share_bytes_saved=(ss or {}).get("bytes_saved", 0),
        )

    base = run("bf16", pages_base, None, False)
    shared = run("bf16", pages_shared, share, False)
    composed = run("int8", pages_int8_shared, share, True)
    res = dict(
        label=label, layers=n_layers, share_groups=groups,
        share_hash=share.share_hash,
        pool_bytes_saved_frac=round(1 - groups / n_layers, 3),
        base_bf16=base, shared_bf16=shared,
        shared_int8_cold_spill=composed,
        shared_vs_base=round(
            shared["peak_live_sessions"]
            / max(base["peak_live_sessions"], 1), 2),
        composed_vs_base=round(
            composed["peak_live_sessions"]
            / max(base["peak_live_sessions"], 1), 2),
        equal_bytes=shared["pool_bytes"] <= base["pool_bytes"],
    )
    log(f"[{label}] kv-share capacity: {n_layers} layers -> {groups} "
        f"groups ({res['pool_bytes_saved_frac']:.0%} pool bytes saved); "
        f"live sessions base={base['peak_live_sessions']} "
        f"shared={shared['peak_live_sessions']} "
        f"shared+int8+spill={composed['peak_live_sessions']} "
        f"({res['composed_vs_base']}x vs base, equal bytes: "
        f"{res['equal_bytes']})")
    return res


def measure_kv_compressed_transport(label: str) -> dict:
    """Compressed-latent KV transport (kv_compress.py): the bytes the
    fleet actually moves. One KVPageBlock payload is what every
    byte-moving path ships — disagg phase-2 handoff, KVSpillTier flush,
    prefix-store demotion, federation blob — so this phase builds the
    same tiny DeepSeek-V2 in both MLA cache modes (``compressed`` gets
    the latent codec automatically, ``full`` ships raw per-head pages),
    populates each paged pool with a real generate, then times and
    sizes the transport primitives per mode: export+to_host (the
    handoff/spill/demotion encode), to_bytes (the federation wire),
    import_block (the decode-side land), and a sync KVSpillTier
    put/take. A fault leg arms cache.compress on the latent engine and
    records the counted ship-raw degradation. The headline is the
    MLA-native byte ratio: same tokens, ~num_heads x fewer bytes on the
    wire, bit-exactly."""
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.cache import KVCache
    from mlx_sharding_tpu.config import DeepseekV2Config
    from mlx_sharding_tpu.kv_transfer import (
        KVSpillTier,
        export_block,
        import_block,
    )
    from mlx_sharding_tpu.models.deepseek_v2 import DeepseekV2Model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from mlx_sharding_tpu.testing import faults

    page_size = 8
    pool_pages = 10
    pages = [1, 2, 3, 4]
    n_tok = len(pages) * page_size
    reps = 15

    def build(mode: str):
        cfg = DeepseekV2Config(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=16, num_hidden_layers=4,
            num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=16,
            q_lora_rank=None, qk_rope_head_dim=8, qk_nope_head_dim=16,
            v_head_dim=12, n_routed_experts=4, n_shared_experts=1,
            num_experts_per_tok=2, first_k_dense_replace=1,
            mla_cache_mode=mode,
        )
        model = DeepseekV2Model(cfg)
        params = model.init_params(jax.random.PRNGKey(7), jnp.float32)
        eng = PipelineEngine(
            model, params, make_mesh(pp=1, devices=jax.devices()[:1]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8, pool_pages=pool_pages, page_size=page_size,
        )
        return eng, ContinuousBatcher(eng, decode_block=3)

    def run(mode: str) -> dict:
        eng, batcher = build(mode)
        try:
            prompt = [int(x) for x in
                      np.random.default_rng(9).integers(1, 100, 24)]
            for _ in batcher.generate_step(prompt, max_tokens=page_size):
                pass  # leaves real KV in the pool pages
            codec = eng.kv_codec
            cache = batcher.cache
            kw = dict(page_size=page_size, n_tokens=n_tok,
                      prompt=prompt[:3], history=[1] * (n_tok - 3),
                      produced=n_tok - 3, resume_keys=None,
                      resume_recent=None, codec=codec)
            dst = KVCache(k=jax.tree.map(jnp.zeros_like, cache.k),
                          v=jax.tree.map(jnp.zeros_like, cache.v),
                          offset=jnp.zeros((), jnp.int32))
            exp_ms, imp_ms, wire_ms, spill_ms = [], [], [], []
            blk = wire = None
            tier = KVSpillTier(64 << 20, flush_async=False)
            for i in range(reps):
                t0 = time.perf_counter()
                blk = export_block(cache, pages, **kw).to_host()
                exp_ms.append((time.perf_counter() - t0) * 1e3)
                t0 = time.perf_counter()
                wire = blk.to_bytes()
                wire_ms.append((time.perf_counter() - t0) * 1e3)
                t0 = time.perf_counter()
                import_block(dst, blk, pages, codec=codec)
                imp_ms.append((time.perf_counter() - t0) * 1e3)
                t0 = time.perf_counter()
                tier.put(f"b{i}", export_block(cache, pages, **kw))
                tier.take(f"b{i}")
                spill_ms.append((time.perf_counter() - t0) * 1e3)
            ts = tier.stats()
            tier.close()
            res = dict(
                mode=mode,
                compress_kind=blk.compress_kind,
                block_host_bytes=int(blk.nbytes),
                wire_bytes=len(wire),
                wire_bytes_per_token=round(len(wire) / n_tok, 1),
                handoff_export_p50_ms=round(statistics.median(exp_ms), 3),
                handoff_import_p50_ms=round(statistics.median(imp_ms), 3),
                federation_wire_p50_ms=round(statistics.median(wire_ms), 3),
                spill_put_take_p50_ms=round(statistics.median(spill_ms), 3),
                spill_bytes_compress_saved=int(
                    ts.get("bytes_compress_saved", 0)),
            )
            if codec is not None:
                # exactness + fault legs ride the latent engine only
                a = import_block(dst, blk, pages, codec=codec)
                b = import_block(dst, export_block(
                    cache, pages, **dict(kw, codec=None)).to_host(), pages)
                res["bit_exact"] = all(
                    np.array_equal(np.asarray(x), np.asarray(y))
                    for x, y in zip(jax.tree.leaves((a.k, a.v)),
                                    jax.tree.leaves((b.k, b.v))))
                faults.arm("cache.compress", exc=faults.FaultError, times=1)
                raw = export_block(cache, pages, **kw).to_host()
                faults.disarm()
                res["fault_leg"] = dict(
                    shipped_kind=raw.compress_kind,  # None: shipped RAW
                    compress_faults=codec.stats()["compress_faults"],
                )
            return res
        finally:
            batcher.close()

    latent = run("compressed")
    full = run("full")
    ratio = round(full["wire_bytes"] / max(latent["wire_bytes"], 1), 2)
    res = dict(
        label=label, tokens_moved=n_tok,
        compressed=latent, full=full,
        mla_native_byte_reduction_x=ratio,
    )
    log(f"[{label}] kv compressed transport: {n_tok} tokens move "
        f"{latent['wire_bytes']}B latent vs {full['wire_bytes']}B full "
        f"({ratio}x fewer bytes), export p50 "
        f"{latent['handoff_export_p50_ms']}ms vs "
        f"{full['handoff_export_p50_ms']}ms, bit_exact="
        f"{latent.get('bit_exact')}, fault leg shipped "
        f"{latent.get('fault_leg', {}).get('shipped_kind')} (raw) with "
        f"{latent.get('fault_leg', {}).get('compress_faults')} counted")
    return res


def measure_paged_ragged_vs_gather(model, params, label: str) -> dict:
    """The ragged paged-attention A/B (ISSUE 1 tentpole): mixed-length
    continuous batching decode through the same page pool on both paths.
    Ragged attends over the pool in place via the slot page tables
    (ops/paged_attention.py); gather materializes each slot's contiguous
    max_seq view per tick and scatters the dirty page back. Records decode
    tok/s and the scheduler's analytic KV-bytes-read accounting for each —
    the bytes ratio is the traffic the ragged path deletes, the tok/s ratio
    is what that buys on the current backend (CPU exercises the XLA
    fallbacks; the Pallas kernel needs a real chip)."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    vocab = model.config.vocab_size
    rng = np.random.default_rng(11)
    # uneven on purpose: slots at very different lengths are the whole case
    # for ragged (gather pays max_seq for every one of them)
    lens = [16, 64, 160, 320]
    prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, n)] for n in lens
    ]

    def run(path: str) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=4,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=28, page_size=128, paged_attention=path,
        )
        batcher = ContinuousBatcher(eng, decode_block=8)
        try:
            for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
                pass  # compile prefill + the decode block for this path
            total = [0]
            lock = threading.Lock()

            def consume(p):
                n = sum(1 for _ in batcher.generate_step(p, max_tokens=48))
                with lock:
                    total[0] += n

            threads = [
                threading.Thread(target=consume, args=(p,)) for p in prompts
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            kpath, last, total_bytes = batcher.kv_read_stats()
        finally:
            batcher.close()
        return dict(
            path=kpath, tok_s=round(total[0] / wall, 1),
            kv_bytes_last_tick=int(last),
            kv_bytes_read_total=int(total_bytes),
        )

    ragged = run("ragged")
    gather = run("gather")
    res = dict(
        label=label, ragged=ragged, gather=gather,
        tok_s_ratio=round(ragged["tok_s"] / max(gather["tok_s"], 1e-9), 2),
        kv_bytes_ratio=round(
            gather["kv_bytes_read_total"]
            / max(ragged["kv_bytes_read_total"], 1), 2,
        ),
    )
    log(f"[{label}] ragged={ragged['tok_s']} tok/s "
        f"({ragged['path']}) gather={gather['tok_s']} tok/s — "
        f"{res['tok_s_ratio']}x speed, {res['kv_bytes_ratio']}x less KV "
        "traffic")
    return res


def measure_kv_int8_vs_bf16(model, params, label: str) -> dict:
    """Equal-HBM A/B for the int8 paged KV pool (quantized-memory-hierarchy
    tentpole): size an int8 pool to the same byte budget as a bf16 pool —
    an int8 row-head is D codes + one f32 scale vs 2D bytes of bf16, so the
    same budget holds ~2D/(D+4)x the pages — then run the same mixed-length
    continuously-batched decode through both and record pool capacity
    (tokens), measured pool bytes, aggregate tok/s, and the scheduler's
    live weight/KV bytes-per-token gauges. Capacity is the headline here:
    tok/s parity says quantization costs nothing, the capacity ratio says
    what the freed bytes buy (CPU exercises the XLA fallbacks; kernel
    dequant needs a real chip)."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    d = model.config.head_dim
    page_size = 128
    pages_bf16 = 16
    pages_int8 = int(pages_bf16 * (2 * d) / (d + 4))
    vocab = model.config.vocab_size
    rng = np.random.default_rng(13)
    prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, n)]
        for n in (24, 48, 96, 160)
    ]

    def run(kv_dtype: str, pool_pages: int) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=4,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=pool_pages, page_size=page_size, kv_dtype=kv_dtype,
        )
        batcher = ContinuousBatcher(eng, decode_block=8)
        try:
            for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
                pass  # compile prefill + the decode block for this pool
            pool_bytes = sum(
                leaf.nbytes for leaf in
                jax.tree.leaves((batcher.cache.k, batcher.cache.v))
            )
            total = [0]
            lock = threading.Lock()

            def consume(p):
                n = sum(1 for _ in batcher.generate_step(p, max_tokens=32))
                with lock:
                    total[0] += n

            threads = [
                threading.Thread(target=consume, args=(p,)) for p in prompts
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            hbm = batcher.hbm_bytes_per_token_stats() or {}
        finally:
            batcher.close()
        return dict(
            kv_dtype=kv_dtype, pool_pages=pool_pages,
            pool_tokens=pool_pages * page_size, pool_bytes=int(pool_bytes),
            tok_s=round(total[0] / wall, 1),
            weight_bytes_per_token=int(hbm.get("weights", 0)),
            kv_bytes_per_token=int(hbm.get("kv", 0)),
        )

    bf16 = run("bf16", pages_bf16)
    int8 = run("int8", pages_int8)
    res = dict(
        label=label, bf16=bf16, int8=int8,
        capacity_ratio=round(int8["pool_tokens"] / bf16["pool_tokens"], 2),
        pool_bytes_ratio=round(int8["pool_bytes"] / bf16["pool_bytes"], 3),
        tok_s_ratio=round(int8["tok_s"] / max(bf16["tok_s"], 1e-9), 2),
    )
    log(f"[{label}] int8 pool holds {res['capacity_ratio']}x the tokens at "
        f"{res['pool_bytes_ratio']}x the bytes of bf16; decode "
        f"{int8['tok_s']} vs {bf16['tok_s']} tok/s "
        f"({res['tok_s_ratio']}x)")
    return res


def measure_kv_capacity_frontier(model, params, label: str) -> dict:
    """Capacity frontier at fixed pool bytes (proactive-KV-residency
    tentpole): how many concurrent streaming sessions one pool budget can
    keep alive. Three configs at (no more than) the same pool bytes — bf16,
    int8 (~2D/(D+4)x the pages), and int8 + cold-slot spill — are each
    driven by 12 one-page sessions whose consumers stall after the first
    token: the idle-chat shape cold detection targets. A no-spill pool caps
    live sessions at its page count; the spill config parks cold slots
    (pages released, block flushed to host DRAM) so live = resident +
    parked climbs to the whole session set. Live count is sampled from
    public gauges only (pages-in-use + parked; sessions are one page each
    by construction, prefix cache off). A second pass records the resume
    path A/B — wake-to-completion wall and the tick's kv_import stall with
    prefetch staging on vs off; on CPU the counters (prefetch_hits vs
    demand_imports) are the evidence, the milliseconds only illustrate."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    d = model.config.head_dim
    page_size = 128
    pages_bf16 = 4
    pages_int8 = int(pages_bf16 * (2 * d) / (d + 4))
    vocab = model.config.vocab_size
    rng = np.random.default_rng(17)
    sessions = 12
    prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, 8)]
        for _ in range(sessions)
    ]
    spill_kw = dict(spill_bytes=256 << 20, spill_cold_after=2,
                    kv_prefetch="on")

    def _join_all(threads, budget_s):
        end = time.monotonic() + budget_s
        for t in threads:
            t.join(timeout=max(0.0, end - time.monotonic()))

    def run(kv_dtype: str, pool_pages: int, spill: bool) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=8,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=pool_pages, page_size=page_size, kv_dtype=kv_dtype,
        )
        batcher = ContinuousBatcher(
            eng, decode_block=8, **(spill_kw if spill else {})
        )
        stall = threading.Event()
        started = [0]
        lock = threading.Lock()

        def consume(p):
            # prompt 8 + max_tokens 112 < page_size: a one-page session in
            # reserve-mode admission, long enough not to retire mid-window
            gen = batcher.generate_step(p, max_tokens=page_size - 16)
            try:
                next(gen)  # first token: the session is live
                with lock:
                    started[0] += 1
                stall.wait()  # idle mid-stream; backlog builds
            finally:
                gen.close()  # cancel — the resume path is measured below

        threads = [
            threading.Thread(target=consume, args=(p,), daemon=True)
            for p in prompts
        ]
        try:
            for _ in batcher.generate_step(prompts[0], max_tokens=8):
                pass  # compile prefill + the 8-slot decode block
            for t in threads:
                t.start()
            peak = 0
            last_gain = time.monotonic()
            deadline = last_gain + 30.0
            while time.monotonic() < deadline:
                s = batcher.spill_stats() or {}
                _, in_use, _ = batcher.page_stats()
                live = in_use + int(s.get("parked", 0))
                if live > peak:
                    peak, last_gain = live, time.monotonic()
                if peak >= sessions or time.monotonic() - last_gain > 3.0:
                    break
                time.sleep(0.002)
            s = batcher.spill_stats() or {}
            pool_bytes = sum(
                leaf.nbytes for leaf in
                jax.tree.leaves((batcher.cache.k, batcher.cache.v))
            )
            stall.set()
            # consumers still waiting for admission stay blocked on their
            # first token until close() feeds them the shutdown sentinel
            _join_all(threads, 5.0)
        finally:
            batcher.close()
        _join_all(threads, 30.0)
        return dict(
            kv_dtype=kv_dtype, pool_pages=pool_pages,
            pool_bytes=int(pool_bytes), peak_live_sessions=peak,
            sessions_started=started[0],
            cold_spills=int(s.get("cold_spills", 0)),
            parked=int(s.get("parked", 0)),
        )

    def run_resume(kv_prefetch: str) -> dict:
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=2,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
            pool_pages=pages_int8, page_size=page_size, kv_dtype="int8",
        )
        batcher = ContinuousBatcher(
            eng, decode_block=8, **dict(spill_kw, kv_prefetch=kv_prefetch)
        )

        def cycle(p) -> float:
            # one full park/resume round trip: stall until cold-spilled AND
            # host-flushed, then release and time wake -> stream complete
            stall = threading.Event()
            done = [0.0]

            def consume():
                gen = batcher.generate_step(p, max_tokens=48)
                next(gen)
                stall.wait()
                for _ in gen:
                    pass  # drain the backlog; wake, import, finish
                done[0] = time.perf_counter()

            th = threading.Thread(target=consume, daemon=True)
            th.start()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                s = batcher.spill_stats() or {}
                if s.get("parked", 0) > 0 and s.get("blocks_host", 0) > 0:
                    break  # parked AND host-flushed: a true cold resume
                time.sleep(0.002)
            t0 = time.perf_counter()
            stall.set()
            th.join(timeout=60)
            return (done[0] - t0) * 1000.0

        try:
            for _ in batcher.generate_step(prompts[0], max_tokens=8):
                pass  # compile
            cycle(prompts[1])  # warm the wake/import programs (first jit)
            s0 = batcher.spill_stats() or {}
            wall_ms = cycle(prompts[2])
            s = batcher.spill_stats() or {}
            t = batcher.tick_timing_stats()
            return dict(
                kv_prefetch=kv_prefetch,
                resume_wall_ms=round(wall_ms, 1),
                kv_import_ms_last=round(t.get("kv_import_ms_last", 0.0), 3),
                cold_wakes=int(s.get("cold_wakes", 0) - s0.get("cold_wakes", 0)),
                prefetch_hits=int(
                    s.get("prefetch_hits", 0) - s0.get("prefetch_hits", 0)),
                demand_imports=int(
                    s.get("demand_imports", 0) - s0.get("demand_imports", 0)),
                prefetch_faults=int(
                    s.get("prefetch_faults", 0) - s0.get("prefetch_faults", 0)),
            )
        finally:
            batcher.close()

    bf16 = run("bf16", pages_bf16, spill=False)
    int8 = run("int8", pages_int8, spill=False)
    spill = run("int8", pages_int8, spill=True)
    resume_pf = run_resume("on")
    resume_dm = run_resume("off")
    res = dict(
        label=label, sessions=sessions, bf16=bf16, int8=int8,
        int8_cold_spill=spill,
        frontier_vs_bf16=round(
            spill["peak_live_sessions"]
            / max(bf16["peak_live_sessions"], 1), 2),
        int8_vs_bf16=round(
            int8["peak_live_sessions"]
            / max(bf16["peak_live_sessions"], 1), 2),
        resume_prefetch=resume_pf, resume_demand=resume_dm,
    )
    log(f"[{label}] live sessions at fixed pool bytes: "
        f"bf16={bf16['peak_live_sessions']} "
        f"int8={int8['peak_live_sessions']} "
        f"int8+cold-spill={spill['peak_live_sessions']} "
        f"({res['frontier_vs_bf16']}x vs bf16); resume "
        f"prefetch={resume_pf['resume_wall_ms']}ms "
        f"(hits={resume_pf['prefetch_hits']}) vs "
        f"demand={resume_dm['resume_wall_ms']}ms "
        f"(demand={resume_dm['demand_imports']})")
    return res


def measure_overload_shedding(model, params, label: str) -> dict:
    """Goodput under 2x oversubscription (resilience tentpole). A 2-slot
    batcher with a 2-deep admission queue (capacity 4 in flight) is hit by
    8 concurrent clients at once. Without load shedding every client would
    camp on the submit queue and the tail ones would burn their deadline
    budget waiting; with --max-queue the overflow is rejected instantly
    (QueueFullError → HTTP 429 + Retry-After at the server) and the engine
    spends its ticks only on requests that can still meet their deadline.
    Reports completed/shed/timeout splits and goodput tok/s (tokens from
    requests that finished, over batch wall-clock)."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.resilience import QueueFullError, RequestTimeoutError
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    vocab = model.config.vocab_size
    rng = np.random.default_rng(7)
    clients = 8
    prompts = [
        [int(x) for x in rng.integers(1, vocab - 64, 32)]
        for _ in range(clients)
    ]

    eng = PipelineEngine(
        model, params, make_mesh(pp=1), microbatches=2,
        max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
    )
    batcher = ContinuousBatcher(eng, decode_block=8, max_queue=2)
    try:
        for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
            pass  # compile prefill + decode block before the clock starts

        lock = threading.Lock()
        outcome = dict(completed=0, shed=0, timeout=0, good_tokens=0)

        def client(p):
            n = 0
            try:
                # generous total budget: on this backend the admitted
                # requests should finish; the queue bound is what protects
                # them from the other six
                for _ in batcher.generate_step(
                    p, max_tokens=32, request_timeout=120.0
                ):
                    n += 1
                with lock:
                    outcome["completed"] += 1
                    outcome["good_tokens"] += n
            except QueueFullError:
                with lock:
                    outcome["shed"] += 1
            except RequestTimeoutError:
                with lock:
                    outcome["timeout"] += 1

        threads = [
            threading.Thread(target=client, args=(p,)) for p in prompts
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        counters = batcher.resilience_stats()
    finally:
        batcher.close()

    res = dict(
        label=label, clients=clients, slots=2, max_queue=2,
        completed=outcome["completed"], shed=outcome["shed"],
        timeout=outcome["timeout"], wall_s=round(wall, 2),
        goodput_tok_s=round(outcome["good_tokens"] / max(wall, 1e-9), 1),
        shed_queue_full=counters["shed_queue_full"],
        timeouts=counters["timeouts"],
    )
    log(f"[{label}] {clients} clients on 2 slots + 2 queue: "
        f"{res['completed']} completed, {res['shed']} shed (429), "
        f"{res['timeout']} timed out — goodput {res['goodput_tok_s']} tok/s "
        f"in {res['wall_s']}s")
    return res


def measure_async_tick_overlap(model, params, label: str) -> dict:
    """The async tick-pipelining A/B (ISSUE 4 tentpole): the same saturated
    continuous-batching load through the classic dispatch-then-harvest loop
    (``async_sched="off"``) and the double-buffered pipeline
    (``async_sched="on"``), at slots in {2, 4, 8}. Both paths emit identical
    tokens; what changes is where tick wall-time goes. Per tick, sync pays
    host work (dispatch, emit, admission — ``host_ms``, during which the
    device is blocked on the host) PLUS the device wait (``device_blocked``,
    THE tick sync); async dispatches block t+1 first so all of that host
    work runs while the device computes, and only the device wait remains
    on the tick's critical path. ``host_blocked_reduction_pct`` — how much
    of the per-tick host-blocked time (tick_timing_stats ``host_ms_avg``)
    the overlap removed — is the headline (acceptance: >= 40% on CPU
    fallback, aggregate tok/s no worse at slots >= 4); the device wait is
    reported alongside but is irreducible while the device is saturated."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    vocab = model.config.vocab_size
    rng = np.random.default_rng(13)

    res: dict = {"label": label}
    for slots in (2, 4, 8):
        prompts = [
            [int(x) for x in rng.integers(1, vocab - 64, 32)]
            for _ in range(slots)
        ]
        # one engine per slot count, shared by both modes sequentially (the
        # batcher re-derives its cache/slot state from the engine at
        # construction, so close-then-reuse is clean) — the A/B then compares
        # identical compiled programs, only the run loop differs
        eng = PipelineEngine(
            model, params, make_mesh(pp=1), microbatches=slots,
            max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=128,
        )
        entry = {}
        for mode in ("off", "on"):
            batcher = ContinuousBatcher(
                eng, decode_block=8, async_sched=mode
            )
            try:
                for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
                    pass  # compile prefill + the decode block
                # compile lands in the warmup ticks' host_ms (jit lowering
                # blocks the dispatching thread) — drop it from the averages
                batcher.reset_tick_timing()

                done = [0] * slots

                def run(i):
                    for _ in batcher.generate_step(
                        prompts[i], max_tokens=48
                    ):
                        done[i] += 1

                threads = [
                    threading.Thread(target=run, args=(i,))
                    for i in range(slots)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                timing = batcher.tick_timing_stats()
            finally:
                batcher.close()
            entry["async" if mode == "on" else "sync"] = dict(
                aggregate_tps=round(sum(done) / wall, 2),
                host_ms_avg=round(timing["host_ms_avg"], 3),
                device_blocked_ms_avg=round(
                    timing["device_blocked_ms_avg"], 3
                ),
                ticks=timing["ticks"],
            )
        del eng
        sync_h = entry["sync"]["host_ms_avg"]
        async_h = entry["async"]["host_ms_avg"]
        entry["host_blocked_reduction_pct"] = round(
            100.0 * (1.0 - async_h / max(sync_h, 1e-9)), 1
        )
        entry["tps_ratio"] = round(
            entry["async"]["aggregate_tps"]
            / max(entry["sync"]["aggregate_tps"], 1e-9), 3
        )
        res[f"slots{slots}"] = entry
        log(f"[{label}] slots={slots} sync={entry['sync']['aggregate_tps']} "
            f"tok/s (host {sync_h} ms/tick) "
            f"async={entry['async']['aggregate_tps']} tok/s "
            f"(host {async_h} ms/tick) — "
            f"{entry['host_blocked_reduction_pct']}% less host-blocked, "
            f"{entry['tps_ratio']}x tok/s")
    return res


def measure_adaptive_speculation(model, params, label: str) -> dict:
    """Adaptive speculation A/B (ISSUE 16 tentpole): the same saturated
    continuous-batching load with prompt-lookup n-gram drafting at three
    policy points — per-slot adaptive windows (``auto``:
    ``spec_window_max=8``, the acceptance EWMA walks each slot along the
    2/4/8 ladder and disables losers), a pinned bottom-rung window
    (``fixed_w2``: ``spec_window_max=2``, the closest thing to fixed-K
    the tracker admits), and no speculation (``off``) — across an easy
    mix (repetitive prompts; a greedy stream over them settles into
    cycles the proposer catches) and a hard mix (seeded sampled decode:
    novel text, drafts rarely accept). Records aggregate tok/s, p99 ITL
    (per-emit gaps observed stream-side), and each run's accept
    rate/rounds/draft-token spend. Expectation (CPU smoke): auto >=
    fixed_w2 >= off on the easy mix — wider windows where drafts pay —
    and auto ~ off on the hard mix (the tracker disables losing slots
    instead of paying K-wide verifies for junk drafts). N-gram rounds
    ride the async double-buffered tick, so the run also reports the
    resolved scheduler mode."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    vocab = model.config.vocab_size
    rng = np.random.default_rng(29)
    slots = 4
    # long enough for greedy streams to settle into the cycles the
    # proposer feeds on AND for disabled slots to hit the 1 s re-probe
    gen_tokens = 80

    motif = [int(x) for x in rng.integers(1, vocab - 64, 6)]
    mixes = {
        # repeated motif with a per-slot prefix: the trailing n-gram
        # always has an earlier occurrence to continue from
        "easy": [
            [int(rng.integers(1, vocab - 64))] + motif * 7
            for _ in range(slots)
        ],
        "hard": [
            [int(x) for x in rng.integers(1, vocab - 64, 32)]
            for _ in range(slots)
        ],
    }
    modes = {
        "auto": dict(draft="ngram", spec_window_max=8),
        "fixed_w2": dict(draft="ngram", spec_window_max=2),
        "off": dict(),
    }

    eng = PipelineEngine(
        model, params, make_mesh(pp=1), microbatches=slots,
        max_seq=MAX_SEQ, cache_dtype=jnp.bfloat16, prefill_chunk=64,
    )
    res: dict = {"label": label, "slots": slots}
    for mix, prompts in mixes.items():
        sampled = mix == "hard"
        entry = {}
        for mode, kw in modes.items():
            batcher = ContinuousBatcher(eng, decode_block=8, **kw)
            try:
                for _ in batcher.generate_step(prompts[0][:16], max_tokens=8):
                    pass  # compile prefill + decode/verify programs
                gaps: list[list[float]] = [[] for _ in range(slots)]
                done = [0] * slots

                def run(i):
                    kws = (
                        dict(temperature=0.8, seed=1000 + i)
                        if sampled else {}
                    )
                    t_last = time.perf_counter()
                    for _ in batcher.generate_step(
                        prompts[i], max_tokens=gen_tokens, **kws
                    ):
                        now = time.perf_counter()
                        gaps[i].append(now - t_last)
                        t_last = now
                        done[i] += 1

                threads = [
                    threading.Thread(target=run, args=(i,))
                    for i in range(slots)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                st = batcher.spec_stats()
                is_async = bool(getattr(batcher, "_async", False))
            finally:
                batcher.close()
            itls = [g for gs in gaps for g in gs[1:]]  # drop per-slot TTFT
            entry[mode] = dict(
                aggregate_tps=round(sum(done) / wall, 2),
                itl_p99_ms=round(
                    float(np.percentile(itls, 99)) * 1e3, 2
                ) if itls else None,
                async_sched=is_async,
                **(
                    dict(
                        accept_rate=round(st["accept_rate"], 3),
                        rounds=st["rounds"],
                        draft_tokens=st["draft_tokens"],
                        disabled_slots=st.get("disabled_slots"),
                    ) if st is not None else {}
                ),
            )
        entry["auto_vs_off_tps_ratio"] = round(
            entry["auto"]["aggregate_tps"]
            / max(entry["off"]["aggregate_tps"], 1e-9), 3
        )
        entry["auto_vs_fixed_tps_ratio"] = round(
            entry["auto"]["aggregate_tps"]
            / max(entry["fixed_w2"]["aggregate_tps"], 1e-9), 3
        )
        res[mix] = entry
        log(f"[{label}] {mix}: auto={entry['auto']['aggregate_tps']} tok/s "
            f"(accept={entry['auto'].get('accept_rate')}, "
            f"p99 ITL {entry['auto']['itl_p99_ms']}ms) "
            f"fixed_w2={entry['fixed_w2']['aggregate_tps']} "
            f"off={entry['off']['aggregate_tps']} — "
            f"auto/off={entry['auto_vs_off_tps_ratio']}x "
            f"auto/fixed={entry['auto_vs_fixed_tps_ratio']}x")
    del eng
    return res


def kernel_smoke(detail: dict) -> None:
    """Compile (for real) + numerically cross-check both Pallas kernels
    against the XLA paths they replace, and time them."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.ops.attention import causal_attention
    from mlx_sharding_tpu.ops.flash_attention import flash_attention
    from mlx_sharding_tpu.ops.quant import dequantize, quantize_jax
    from mlx_sharding_tpu.ops.quant_matmul import quant_matmul_pallas

    results = {}
    key = jax.random.PRNGKey(0)

    # flash attention: prefill shape and T=1 decode shape
    b, hq, hkv, dk = 1, 24, 8, 128
    s = 1024
    kq, kk, kv = jax.random.split(key, 3)
    k = jax.random.normal(kk, (b, s, hkv, dk), jnp.bfloat16)
    v = jax.random.normal(kv, (b, s, hkv, dk), jnp.bfloat16)

    def timed(fn, n=100):
        """Loop the op N times inside ONE jitted program (scalar-feedback so
        nothing is dead-code-eliminated) — per-launch tunnel overhead here is
        ~1.5-3ms, far above the kernels being measured, so host-side loops
        measure the tunnel, not the kernel."""

        @jax.jit
        def many(eps):
            def body(i, c):
                return c + fn(eps + c * 0.0).astype(jnp.float32).max()

            return jax.lax.fori_loop(0, n, body, jnp.float32(0))

        many(jnp.float32(0)).block_until_ready()
        t0 = time.perf_counter()
        many(jnp.float32(1e-12)).block_until_ready()
        return (time.perf_counter() - t0) / n

    for t, off, name in [(256, 512, "flash_prefill"), (1, 777, "flash_decode")]:
        q = jax.random.normal(kq, (b, t, hq, dk), jnp.bfloat16)
        off_a = jnp.asarray(off, jnp.int32)
        scale = dk ** -0.5
        try:
            t0 = time.perf_counter()
            out = flash_attention(q, k, v, off_a, scale)
            out.block_until_ready()
            compile_s = time.perf_counter() - t0
            # the PRODUCTION fallback (ops.attention fused-XLA path), not a
            # local re-derivation: MST_FLASH=0 steers dispatch at trace time
            os.environ["MST_FLASH"] = "0"
            try:
                ref = causal_attention(q, k, v, off_a, scale)
                err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
                dt_xla = timed(lambda e: causal_attention(q + e.astype(q.dtype), k, v, off_a, scale))
            finally:
                os.environ.pop("MST_FLASH", None)
            dt = timed(lambda e: flash_attention(q + e.astype(q.dtype), k, v, off_a, scale))
            results[name] = dict(
                ok=err < 0.05, max_abs_err=err, compile_s=round(compile_s, 1),
                time_us=round(dt * 1e6, 1), xla_time_us=round(dt_xla * 1e6, 1),
            )
            log(f"[{name}] ok={results[name]['ok']} err={err:.4f} "
                f"time={dt*1e6:.0f}us xla={dt_xla*1e6:.0f}us")
        except Exception as e:  # noqa: BLE001 — record, don't kill the bench
            results[name] = dict(ok=False, error=repr(e)[:300])
            log(f"[{name}] FAILED: {e!r}")

    # fused dequant-matmul vs XLA dequant + matmul
    try:
        out_dim, in_dim, m = 2048, 2048, 128
        w = jax.random.normal(jax.random.PRNGKey(3), (out_dim, in_dim), jnp.float32)
        qw, sc, bi = quantize_jax(w, group_size=64, bits=4)
        x = jax.random.normal(jax.random.PRNGKey(4), (m, in_dim), jnp.bfloat16)
        t0 = time.perf_counter()
        out = quant_matmul_pallas(x, qw, sc, bi, group_size=64, bits=4)
        out.block_until_ready()
        compile_s = time.perf_counter() - t0
        wd = dequantize(qw, sc, bi, group_size=64, bits=4).astype(jnp.bfloat16)
        ref = (x @ wd.T).astype(jnp.float32)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
        rel = err / float(jnp.max(jnp.abs(ref)) + 1e-9)
        dt = timed(
            lambda e: quant_matmul_pallas(
                x + e.astype(x.dtype), qw, sc, bi, group_size=64, bits=4
            )
        )
        dt_dense = timed(lambda e: (x + e.astype(x.dtype)) @ wd.T)
        
        results["quant_matmul"] = dict(ok=rel < 0.02, max_abs_err=err, rel_err=rel, compile_s=round(compile_s, 1), time_us=round(dt * 1e6, 1), dense_time_us=round(dt_dense * 1e6, 1))
        log(f"[quant_matmul] ok={results['quant_matmul']['ok']} rel_err={rel:.5f} time={dt*1e6:.0f}us dense={dt_dense*1e6:.0f}us")
    except Exception as e:  # noqa: BLE001
        results["quant_matmul"] = dict(ok=False, error=repr(e)[:300])
        log(f"[quant_matmul] FAILED: {e!r}")

    detail["kernels"] = results


def main() -> int:
    forced_cpu = os.environ.get("MST_BENCH_FORCED_CPU") == "1"
    cpu_fallback = forced_cpu or not _probe_backend_with_retries()
    if cpu_fallback and not forced_cpu:
        # A wedged axon plugin can hang even a JAX_PLATFORMS=cpu process at
        # backend discovery (observed round 5: jax.devices() blocked with
        # the plugin merely ON PYTHONPATH) — re-exec the fallback with the
        # plugin's site stripped so it cannot inherit the wedge, skipping
        # the probe in the child.
        log("no usable TPU backend — re-exec'ing the CPU fallback with the "
            "axon site stripped from PYTHONPATH")
        env = dict(os.environ)
        env["MST_BENCH_FORCED_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        keep = [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and not any("axon_site" in seg for seg in p.split(os.sep))
        ]
        repo = os.path.dirname(os.path.abspath(__file__))
        if repo not in keep:
            keep.append(repo)
        env["PYTHONPATH"] = os.pathsep.join(keep)
        os.execve(
            sys.executable,
            [sys.executable, os.path.abspath(__file__)],
            env,
        )
    if cpu_fallback:
        # The axon tunnel can be down for reasons outside this repo; a
        # clearly-labeled CPU number beats a hung or absent benchmark.
        log("no usable TPU backend (tunnel hang or CPU-only environment) — "
            "running the CPU fallback with a tiny model; metric name "
            "reflects this")
        # 2 virtual devices so the fallback can also exercise the fused
        # pipeline + continuous batching (must land before jax initializes);
        # respect a caller-set device count
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            )
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp

    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models import build_model

    detail: dict = {
        "device": str(jax.devices()),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_commit": _git_commit(),
    }
    log(f"devices={jax.devices()}")
    cfg_dict = dict(CPU_FALLBACK_MODEL if cpu_fallback else BENCH_MODEL)
    model, cfg = build_model(cfg_dict)
    t0 = time.perf_counter()
    params = jax.jit(lambda k: model.init_params(k, jnp.bfloat16))(
        jax.random.PRNGKey(0)
    )
    jax.block_until_ready(params)
    log(f"params initialized in {time.perf_counter() - t0:.1f}s")

    gen = Generator(model, params, max_seq=MAX_SEQ, prefill_chunk=128)
    prompt = [
        int(t)
        for t in jax.random.randint(
            jax.random.PRNGKey(1), (PROMPT_LEN,), 0, cfg.vocab_size
        )
    ]

    primary = measure_decode(gen, prompt, "decode_bf16")
    detail["decode_bf16"] = primary

    if cpu_fallback:
        # cover more than the single-chip path even when the tunnel is
        # down: the fused 2-stage pipeline and 2-slot continuous batching
        # on a forced 2-device CPU "mesh" (labeled, vs_baseline 0)
        try:
            if len(jax.devices()) >= 2:
                from mlx_sharding_tpu.parallel.mesh import make_mesh
                from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

                eng = PipelineEngine(
                    model, params, make_mesh(pp=2), max_seq=MAX_SEQ,
                    cache_dtype=jnp.bfloat16, prefill_chunk=128,
                )
                detail["decode_pp2_cpu"] = measure_decode(
                    eng, prompt, "decode_pp2_cpu"
                )
                del eng
                detail["decode_cb2_cpu"] = measure_cb(
                    model, params, prompt, "decode_cb2_cpu", slots=2
                )
        except Exception as e:  # noqa: BLE001
            detail["cpu_fallback_extras"] = dict(error=repr(e)[:300])
            log(f"[cpu_fallback_extras] FAILED: {e!r}")
        # prefix-cache + over-commit evidence ride the fallback too, on a
        # smaller model (the 0.28B fallback compiles these paths too slowly
        # on CPU to fit the bench budget) — the structural deltas (chunk
        # skip, admission interleaving) are what these record, not tok/s.
        # Guarded like every other measurement: a failure here must never
        # cost the artifact/headline writes below.
        m2 = p2 = None
        try:
            tiny2 = dict(
                model_type="llama", vocab_size=4096, hidden_size=128,
                intermediate_size=256, num_hidden_layers=4,
                num_attention_heads=4, num_key_value_heads=2, head_dim=32,
                max_position_embeddings=2048,
            )
            m2, _ = build_model(tiny2)
            p2 = jax.jit(lambda k: m2.init_params(k, jnp.bfloat16))(
                jax.random.PRNGKey(2)
            )
        except Exception as e:  # noqa: BLE001
            detail["cb_prefix_cache_cpu"] = dict(error=repr(e)[:300])
            log(f"[cpu tiny2 build] FAILED: {e!r}")
        if m2 is not None:
            try:
                detail["cb_prefix_cache_cpu"] = measure_cb_prefix(
                    m2, p2, "cb_prefix_cache_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["cb_prefix_cache_cpu"] = dict(error=repr(e)[:300])
                log(f"[cb_prefix_cache_cpu] FAILED: {e!r}")
            try:
                detail["cb_overcommit_cpu"] = measure_cb_overcommit(
                    m2, p2, "cb_overcommit_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["cb_overcommit_cpu"] = dict(error=repr(e)[:300])
                log(f"[cb_overcommit_cpu] FAILED: {e!r}")
            try:
                detail["paged_ragged_vs_gather_cpu"] = (
                    measure_paged_ragged_vs_gather(
                        m2, p2, "paged_ragged_vs_gather_cpu"
                    )
                )
            except Exception as e:  # noqa: BLE001
                detail["paged_ragged_vs_gather_cpu"] = dict(
                    error=repr(e)[:300]
                )
                log(f"[paged_ragged_vs_gather_cpu] FAILED: {e!r}")
            try:
                detail["overload_shedding_cpu"] = measure_overload_shedding(
                    m2, p2, "overload_shedding_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["overload_shedding_cpu"] = dict(error=repr(e)[:300])
                log(f"[overload_shedding_cpu] FAILED: {e!r}")
            try:
                detail["preempt_spill_vs_discard_cpu"] = (
                    measure_preempt_spill_vs_discard(
                        m2, p2, "preempt_spill_vs_discard_cpu"
                    )
                )
            except Exception as e:  # noqa: BLE001
                detail["preempt_spill_vs_discard_cpu"] = dict(
                    error=repr(e)[:300]
                )
                log(f"[preempt_spill_vs_discard_cpu] FAILED: {e!r}")
            try:
                detail["replica_drain_cpu"] = measure_replica_drain(
                    m2, p2, "replica_drain_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["replica_drain_cpu"] = dict(error=repr(e)[:300])
                log(f"[replica_drain_cpu] FAILED: {e!r}")
            try:
                detail["fleet_elasticity_cpu"] = measure_fleet_elasticity(
                    m2, p2, "fleet_elasticity_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["fleet_elasticity_cpu"] = dict(error=repr(e)[:300])
                log(f"[fleet_elasticity_cpu] FAILED: {e!r}")
            try:
                detail["weight_sharing_cpu"] = measure_weight_sharing(
                    m2, p2, "weight_sharing_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["weight_sharing_cpu"] = dict(error=repr(e)[:300])
                log(f"[weight_sharing_cpu] FAILED: {e!r}")
            try:
                detail["disagg_prefill_decode_cpu"] = (
                    measure_disagg_prefill_decode(
                        m2, p2, "disagg_prefill_decode_cpu"
                    )
                )
            except Exception as e:  # noqa: BLE001
                detail["disagg_prefill_decode_cpu"] = dict(error=repr(e)[:300])
                log(f"[disagg_prefill_decode_cpu] FAILED: {e!r}")
            try:
                detail["pod_fleet_cpu"] = measure_pod_fleet(
                    m2, p2, "pod_fleet_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["pod_fleet_cpu"] = dict(error=repr(e)[:300])
                log(f"[pod_fleet_cpu] FAILED: {e!r}")
            try:
                detail["pod_prefix_federation_cpu"] = (
                    measure_pod_prefix_federation(
                        m2, p2, "pod_prefix_federation_cpu"
                    )
                )
            except Exception as e:  # noqa: BLE001
                detail["pod_prefix_federation_cpu"] = dict(
                    error=repr(e)[:300]
                )
                log(f"[pod_prefix_federation_cpu] FAILED: {e!r}")
            try:
                detail["trace_overhead_cpu"] = measure_trace_overhead(
                    m2, p2, "trace_overhead_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["trace_overhead_cpu"] = dict(error=repr(e)[:300])
                log(f"[trace_overhead_cpu] FAILED: {e!r}")
            # the 0.28B fallback model, not tiny2: the A/B needs decode
            # blocks whose device time is non-trivial next to the host work,
            # or there is nothing for the async loop to overlap
            try:
                detail["async_tick_overlap_cpu"] = (
                    measure_async_tick_overlap(
                        model, params, "async_tick_overlap_cpu"
                    )
                )
            except Exception as e:  # noqa: BLE001
                detail["async_tick_overlap_cpu"] = dict(error=repr(e)[:300])
                log(f"[async_tick_overlap_cpu] FAILED: {e!r}")
            # n-gram speculation's win is fewer rounds, not cheaper
            # forwards, so the tiny model measures the policy fine
            try:
                detail["adaptive_speculation_cpu"] = (
                    measure_adaptive_speculation(
                        m2, p2, "adaptive_speculation_cpu"
                    )
                )
            except Exception as e:  # noqa: BLE001
                detail["adaptive_speculation_cpu"] = dict(error=repr(e)[:300])
                log(f"[adaptive_speculation_cpu] FAILED: {e!r}")
            # int8-KV equal-memory A/B: needs head_dim >= 64 for its
            # capacity claim (the ratio is 2D/(D+4): D=32 caps at 1.78x,
            # D=64 gives 1.88x), so this phase gets its own tiny variant
            m3 = p3 = None
            try:
                tiny64 = dict(tiny2, num_attention_heads=2,
                              num_key_value_heads=2, head_dim=64)
                m3, _ = build_model(tiny64)
                p3 = jax.jit(lambda k: m3.init_params(k, jnp.bfloat16))(
                    jax.random.PRNGKey(3)
                )
                detail["kv_int8_vs_bf16_cpu"] = measure_kv_int8_vs_bf16(
                    m3, p3, "kv_int8_vs_bf16_cpu"
                )
            except Exception as e:  # noqa: BLE001
                detail["kv_int8_vs_bf16_cpu"] = dict(error=repr(e)[:300])
                log(f"[kv_int8_vs_bf16_cpu] FAILED: {e!r}")
            # the capacity frontier rides the same head_dim-64 variant:
            # its equal-byte int8 page math needs D >= 64 too
            if m3 is not None:
                try:
                    detail["kv_capacity_frontier_cpu"] = (
                        measure_kv_capacity_frontier(
                            m3, p3, "kv_capacity_frontier_cpu"
                        )
                    )
                except Exception as e:  # noqa: BLE001
                    detail["kv_capacity_frontier_cpu"] = dict(
                        error=repr(e)[:300]
                    )
                    log(f"[kv_capacity_frontier_cpu] FAILED: {e!r}")
                # prefix-store reuse rides it too: the composed frontier
                # leg shares the frontier's D >= 64 int8 page math
                try:
                    detail["prefix_reuse_ttft_cpu"] = (
                        measure_prefix_reuse_ttft(
                            m3, p3, "prefix_reuse_ttft_cpu"
                        )
                    )
                except Exception as e:  # noqa: BLE001
                    detail["prefix_reuse_ttft_cpu"] = dict(
                        error=repr(e)[:300]
                    )
                    log(f"[prefix_reuse_ttft_cpu] FAILED: {e!r}")
                # layer-wise KV sharing composes with the frontier's
                # head_dim-64 variant: the int8 leg's page math needs it
                try:
                    detail["kv_share_capacity_cpu"] = (
                        measure_kv_share_capacity(
                            m3, p3, "kv_share_capacity_cpu"
                        )
                    )
                except Exception as e:  # noqa: BLE001
                    detail["kv_share_capacity_cpu"] = dict(
                        error=repr(e)[:300]
                    )
                    log(f"[kv_share_capacity_cpu] FAILED: {e!r}")
        # compressed-latent transport builds its own tiny DeepSeek-V2
        # pair (MLA compressed vs full cache modes) — independent of the
        # llama tiny variants above
        try:
            detail["kv_compressed_transport_cpu"] = (
                measure_kv_compressed_transport("kv_compressed_transport_cpu")
            )
        except Exception as e:  # noqa: BLE001
            detail["kv_compressed_transport_cpu"] = dict(error=repr(e)[:300])
            log(f"[kv_compressed_transport_cpu] FAILED: {e!r}")

    if not cpu_fallback:
        n_params = param_count(cfg_dict)
        tps = primary["decode_tps"]
        mbu = tps * n_params * 2 / V5E_PEAK_HBM_BYTES
        mfu = tps * n_params * 2 / V5E_PEAK_BF16_FLOPS
        detail["roofline"] = dict(
            params=n_params,
            mbu=round(mbu, 3),
            mfu=round(mfu, 4),
            note="decode is HBM-bound; MBU is the meaningful utilization",
        )
        log(f"params={n_params/1e9:.2f}B MBU={mbu:.1%} MFU={mfu:.2%}")

        # flash-decode A/B on the same generator (env flag steers dispatch)
        os.environ["MST_FLASH_DECODE"] = "1"
        try:
            gen_fd = Generator(model, params, max_seq=MAX_SEQ, prefill_chunk=128)
            detail["decode_bf16_flash_decode"] = measure_decode(
                gen_fd, prompt, "decode_bf16_flash_decode"
            )
        except Exception as e:  # noqa: BLE001
            detail["decode_bf16_flash_decode"] = dict(error=repr(e)[:300])
            log(f"[decode_bf16_flash_decode] FAILED: {e!r}")
        finally:
            os.environ.pop("MST_FLASH_DECODE", None)

        # flash-prefill e2e A/B: per-kernel µs through the tunnel is too
        # noisy to trust (observed 397↔880 µs across runs) — prompt_tps /
        # TTFT with the kernel OFF is the decision-grade comparison for the
        # MST_FLASH default
        os.environ["MST_FLASH"] = "0"
        try:
            gen_nf = Generator(model, params, max_seq=MAX_SEQ, prefill_chunk=128)
            detail["decode_bf16_no_flash_prefill"] = measure_decode(
                gen_nf, prompt, "decode_bf16_no_flash_prefill"
            )
        except Exception as e:  # noqa: BLE001
            detail["decode_bf16_no_flash_prefill"] = dict(error=repr(e)[:300])
            log(f"[decode_bf16_no_flash_prefill] FAILED: {e!r}")
        finally:
            os.environ.pop("MST_FLASH", None)

        kernel_smoke(detail)

        # packed-4bit resident decode: quantize the decoder weights on device,
        # keep them packed, decode through ops.quant.linear's packed path —
        # the same residency --keep-quantized gives real 4-bit checkpoints
        try:
            from mlx_sharding_tpu.ops.quant import quantize_jax

            pack = jax.jit(
                lambda w: quantize_jax(jnp.swapaxes(w, -1, -2))  # (L,in,out)→(L,out,in) mlx orientation
            )
            qlayers = {}
            for name, wstack in params["layers"].items():
                if getattr(wstack, "ndim", 0) == 3 and "norm" not in name:
                    q, s, b = pack(wstack)
                    qlayers[name] = {"q": q, "scales": s, "biases": b}
                else:
                    qlayers[name] = wstack
            qparams = dict(params, layers=qlayers)
            jax.block_until_ready(qparams)
            gen_q = Generator(model, qparams, max_seq=MAX_SEQ, prefill_chunk=128)
            detail["decode_4bit_packed"] = measure_decode(
                gen_q, prompt, "decode_4bit_packed"
            )
            detail["decode_4bit_packed"].update(hbm_bytes_per_token(
                cfg_dict, weight_bits=4, kv_dtype="bf16", batch=1,
                context=PROMPT_LEN + DECODE_TOKENS,
            ))
        except Exception as e:  # noqa: BLE001
            detail["decode_4bit_packed"] = dict(error=repr(e)[:300])
            log(f"[decode_4bit_packed] FAILED: {e!r}")

        # Larger decode blocks hide the host pull behind device compute
        # (one-block lookahead): the pull is ~97 ms through this tunnel vs
        # ~40 ms of device compute per 16-token block, so the packed path —
        # whose device step is far cheaper than bf16's — only shows its
        # bandwidth win once block compute exceeds the pull.
        try:
            gen_q64 = Generator(
                model, qparams, max_seq=MAX_SEQ, prefill_chunk=128,
                decode_block=64,
            )
            detail["decode_4bit_packed_block64"] = measure_decode(
                gen_q64, prompt, "decode_4bit_packed_block64"
            )
            detail["decode_4bit_packed_block64"].update(hbm_bytes_per_token(
                cfg_dict, weight_bits=4, kv_dtype="bf16", batch=1,
                context=PROMPT_LEN + DECODE_TOKENS,
            ))
        except Exception as e:  # noqa: BLE001
            detail["decode_4bit_packed_block64"] = dict(error=repr(e)[:300])
            log(f"[decode_4bit_packed_block64] FAILED: {e!r}")

        try:
            gen64 = Generator(
                model, params, max_seq=MAX_SEQ, prefill_chunk=128,
                decode_block=64,
            )
            detail["decode_bf16_block64"] = measure_decode(
                gen64, prompt, "decode_bf16_block64"
            )
        except Exception as e:  # noqa: BLE001
            detail["decode_bf16_block64"] = dict(error=repr(e)[:300])
            log(f"[decode_bf16_block64] FAILED: {e!r}")

        # aggregate serving throughput: 4 interleaved requests on the chip.
        # LAST: the engine holds its own sharded param copy + the M-slot KV
        # pool — running it earlier starves the packed variants of HBM.
        import gc

        gen = gen64 = gen_q = gen_q64 = gen_fd = gen_nf = None  # noqa: F841
        qparams = qlayers = None  # noqa: F841
        gc.collect()
        try:
            detail["decode_bf16_cb4"] = measure_cb(
                model, params, prompt, "decode_bf16_cb4", slots=4
            )
        except Exception as e:  # noqa: BLE001
            detail["decode_bf16_cb4"] = dict(error=repr(e)[:300])
            log(f"[decode_bf16_cb4] FAILED: {e!r}")
        gc.collect()
        try:
            detail["cb_prefix_cache"] = measure_cb_prefix(
                model, params, "cb_prefix_cache"
            )
        except Exception as e:  # noqa: BLE001
            detail["cb_prefix_cache"] = dict(error=repr(e)[:300])
            log(f"[cb_prefix_cache] FAILED: {e!r}")
        gc.collect()
        try:
            detail["prefix_reuse_ttft"] = measure_prefix_reuse_ttft(
                model, params, "prefix_reuse_ttft"
            )
        except Exception as e:  # noqa: BLE001
            detail["prefix_reuse_ttft"] = dict(error=repr(e)[:300])
            log(f"[prefix_reuse_ttft] FAILED: {e!r}")
        gc.collect()
        try:
            detail["cb_overcommit"] = measure_cb_overcommit(
                model, params, "cb_overcommit"
            )
        except Exception as e:  # noqa: BLE001
            detail["cb_overcommit"] = dict(error=repr(e)[:300])
            log(f"[cb_overcommit] FAILED: {e!r}")
        gc.collect()
        try:
            detail["paged_ragged_vs_gather"] = measure_paged_ragged_vs_gather(
                model, params, "paged_ragged_vs_gather"
            )
        except Exception as e:  # noqa: BLE001
            detail["paged_ragged_vs_gather"] = dict(error=repr(e)[:300])
            log(f"[paged_ragged_vs_gather] FAILED: {e!r}")
        gc.collect()
        try:
            detail["overload_shedding"] = measure_overload_shedding(
                model, params, "overload_shedding"
            )
        except Exception as e:  # noqa: BLE001
            detail["overload_shedding"] = dict(error=repr(e)[:300])
            log(f"[overload_shedding] FAILED: {e!r}")
        gc.collect()
        try:
            detail["adaptive_speculation"] = measure_adaptive_speculation(
                model, params, "adaptive_speculation"
            )
        except Exception as e:  # noqa: BLE001
            detail["adaptive_speculation"] = dict(error=repr(e)[:300])
            log(f"[adaptive_speculation] FAILED: {e!r}")
        gc.collect()
        try:
            detail["async_tick_overlap"] = measure_async_tick_overlap(
                model, params, "async_tick_overlap"
            )
        except Exception as e:  # noqa: BLE001
            detail["async_tick_overlap"] = dict(error=repr(e)[:300])
            log(f"[async_tick_overlap] FAILED: {e!r}")
        gc.collect()
        try:
            detail["kv_int8_vs_bf16"] = measure_kv_int8_vs_bf16(
                model, params, "kv_int8_vs_bf16"
            )
        except Exception as e:  # noqa: BLE001
            detail["kv_int8_vs_bf16"] = dict(error=repr(e)[:300])
            log(f"[kv_int8_vs_bf16] FAILED: {e!r}")
        gc.collect()
        try:
            detail["fleet_elasticity"] = measure_fleet_elasticity(
                model, params, "fleet_elasticity"
            )
        except Exception as e:  # noqa: BLE001
            detail["fleet_elasticity"] = dict(error=repr(e)[:300])
            log(f"[fleet_elasticity] FAILED: {e!r}")
        gc.collect()
        try:
            detail["weight_sharing"] = measure_weight_sharing(
                model, params, "weight_sharing"
            )
        except Exception as e:  # noqa: BLE001
            detail["weight_sharing"] = dict(error=repr(e)[:300])
            log(f"[weight_sharing] FAILED: {e!r}")
        gc.collect()
        try:
            # self-skips on a single-chip host (needs one device per pool)
            detail["disagg_prefill_decode"] = measure_disagg_prefill_decode(
                model, params, "disagg_prefill_decode"
            )
        except Exception as e:  # noqa: BLE001
            detail["disagg_prefill_decode"] = dict(error=repr(e)[:300])
            log(f"[disagg_prefill_decode] FAILED: {e!r}")
        gc.collect()
        try:
            # loopback 2-"host" pod smoke on one real chip pair: aliased
            # weight bytes, cross-host handoff latency, kill-storm drain
            detail["pod_fleet"] = measure_pod_fleet(
                model, params, "pod_fleet"
            )
        except Exception as e:  # noqa: BLE001
            detail["pod_fleet"] = dict(error=repr(e)[:300])
            log(f"[pod_fleet] FAILED: {e!r}")
        try:
            detail["pod_prefix_federation"] = measure_pod_prefix_federation(
                model, params, "pod_prefix_federation"
            )
        except Exception as e:  # noqa: BLE001
            detail["pod_prefix_federation"] = dict(error=repr(e)[:300])
            log(f"[pod_prefix_federation] FAILED: {e!r}")
        try:
            detail["kv_share_capacity"] = measure_kv_share_capacity(
                model, params, "kv_share_capacity"
            )
        except Exception as e:  # noqa: BLE001
            detail["kv_share_capacity"] = dict(error=repr(e)[:300])
            log(f"[kv_share_capacity] FAILED: {e!r}")
        try:
            detail["kv_compressed_transport"] = (
                measure_kv_compressed_transport("kv_compressed_transport")
            )
        except Exception as e:  # noqa: BLE001
            detail["kv_compressed_transport"] = dict(error=repr(e)[:300])
            log(f"[kv_compressed_transport] FAILED: {e!r}")

        # HEADLINE (BASELINE.json primary config): DeepSeek-Coder-V2-Lite at
        # its real architecture and scale — 27 layers, 64-expert MoE + 2
        # shared, compressed-MLA cache, packed 4-bit resident (~10 GB HBM) —
        # single-chip decode. Weights are synthetic (synth_packed_deepseek;
        # the checkpoint bytes are unobtainable in this zero-egress
        # environment — BASELINE.md round 5) in the byte-exact
        # keep_quantized layout; decode throughput is value-independent.
        # LAST: needs the 3B model's HBM back first.
        model = params = None
        gc.collect()
        try:
            import numpy as _np

            dmodel, _dcfg = build_model(DSV2_LITE)
            dparams = synth_packed_deepseek(dmodel, jax.random.PRNGKey(11))
            jax.block_until_ready(dparams)
            dgen = Generator(
                dmodel, dparams, max_seq=MAX_SEQ, prefill_chunk=128
            )
            dprompt = [
                int(x) for x in
                _np.random.default_rng(5).integers(1, 50000, PROMPT_LEN)
            ]
            detail["deepseek_v2_lite_4bit"] = dict(
                measure_decode(dgen, dprompt, "deepseek_v2_lite_4bit"),
                note="BASELINE primary arch at real scale, synthetic packed "
                     "weights (zero-egress: no checkpoint bytes available); "
                     "~2.4B activated params/token of ~15.7B total",
            )
            dgen = dparams = dmodel = None
            gc.collect()
        except Exception as e:  # noqa: BLE001
            detail["deepseek_v2_lite_4bit"] = dict(error=repr(e)[:300])
            log(f"[deepseek_v2_lite_4bit] FAILED: {e!r}")

    # quantized-memory-hierarchy accounting (analytic, so it lands in
    # every BENCH_DETAIL* regardless of backend): the 4-bit + int8-KV
    # serving config vs the 4-bit + bf16-KV one it replaces, at the 3B
    # BENCH_MODEL's serving point — 32 batched slots amortizing the weight
    # stream, 4096-token context dominating the KV stream
    a = hbm_bytes_per_token(BENCH_MODEL, weight_bits=4, kv_dtype="bf16",
                            batch=32, context=4096)
    b = hbm_bytes_per_token(BENCH_MODEL, weight_bits=4, kv_dtype="int8",
                            batch=32, context=4096)
    ta = a["weight_bytes_per_token"] + a["kv_bytes_per_token"]
    tb = b["weight_bytes_per_token"] + b["kv_bytes_per_token"]
    detail["quant_memory_hierarchy"] = dict(
        config_4bit_bf16kv=a, config_4bit_int8kv=b,
        total_bytes_per_token_reduction_pct=round(100 * (1 - tb / ta), 1),
    )
    log(f"[quant_memory_hierarchy] 4bit+int8KV reads "
        f"{detail['quant_memory_hierarchy']['total_bytes_per_token_reduction_pct']}% "
        f"fewer HBM bytes/token than 4bit+bf16KV at batch 32 / ctx 4096")

    detail_path = DETAIL_PATH
    if cpu_fallback and os.path.exists(DETAIL_PATH):
        try:
            with open(DETAIL_PATH) as f:
                if _is_real_chip_detail(json.load(f)):
                    # never clobber real-chip evidence with a fallback run —
                    # the tunnel wedges intermittently (BASELINE.md)
                    detail_path = DETAIL_PATH.replace(".json", "_CPU.json")
        except (OSError, ValueError):
            pass
    # provenance is (re-)stamped at WRITE time, not dict-creation time: a
    # real-chip sweep runs long enough that the creation-time stamp predates
    # the numbers it describes, and the carry-forward reader
    # (_last_good_real_chip) treats these two fields as the measurement's
    # identity — they must describe the moment the file's contents were final
    detail["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    detail["git_commit"] = _git_commit()
    with open(detail_path, "w") as f:
        json.dump(detail, f, indent=1)
    log(f"detail written to {detail_path}")

    if not cpu_fallback:
        print(
            json.dumps(
                {
                    "metric": "decode_tokens_per_sec_3b_bf16_1chip",
                    "value": primary["decode_tps"],
                    "unit": "tokens/sec",
                    "vs_baseline": round(
                        primary["decode_tps"] / NOMINAL_SINGLE_HOST_MLX_TOKS, 3
                    ),
                }
            )
        )
        return 0

    # Tunnel down for the whole probe budget. If a committed real-chip
    # detail file exists, the headline metric carries it forward with full
    # provenance — a wedge at snapshot time must not erase real evidence
    # (round 3 lost a 102-tok/s record to exactly that). The fresh CPU run
    # above is attached so the artifact also proves the code still works.
    last_good = _last_good_real_chip()
    if last_good is not None:
        print(
            json.dumps(
                {
                    "metric": "decode_tokens_per_sec_3b_bf16_1chip_last_good",
                    "value": last_good["decode_tps"],
                    "unit": "tokens/sec",
                    "vs_baseline": round(
                        last_good["decode_tps"] / NOMINAL_SINGLE_HOST_MLX_TOKS, 3
                    ),
                    "provenance": "last_good_real_chip",
                    "last_good_real_chip": last_good,
                    "fresh_cpu_fallback": {
                        "decode_tps": primary["decode_tps"],
                        "note": "tunnel unreachable this run; tiny-model CPU "
                                "sanity measurement, not comparable to the "
                                "headline value",
                    },
                }
            )
        )
        return 0

    print(
        json.dumps(
            {
                "metric": "decode_tokens_per_sec_tiny_cpu_fallback",
                "value": primary["decode_tps"],
                "unit": "tokens/sec",
                "vs_baseline": 0.0,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
