"""Decode-throughput benchmark on the real TPU chip.

Reproduces the reference's own instrumentation definitions — generation
tok/s = (tokens-1)/decode_time, prompt tok/s, TTFT (ref: generate.py:97-122)
— on this framework's single-chip decode path, with a Llama-3.2-3B-class
model (the largest dense config that fits one v5e chip's HBM in bf16;
the BASELINE.json DeepSeek-Coder-V2-Lite config needs the 8-chip pod this
environment doesn't expose). Weights are randomly initialized on device —
decode throughput is weight-value-independent.

vs_baseline: BASELINE.md records no published reference numbers (the
reference publishes none). The divisor 35.0 tok/s is our documented nominal
for the reference stack (single-host MLX, Apple-silicon, 3B-class bf16
model); vs_baseline > 1.5 meets the BASELINE.json target ratio.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

NOMINAL_SINGLE_HOST_MLX_TOKS = 35.0

BENCH_MODEL = dict(
    model_type="llama",
    vocab_size=128256,
    hidden_size=3072,
    intermediate_size=8192,
    num_hidden_layers=28,
    num_attention_heads=24,
    num_key_value_heads=8,
    head_dim=128,
    tie_word_embeddings=True,
    max_position_embeddings=4096,
)

PROMPT_LEN = 64
DECODE_TOKENS = 128
MAX_SEQ = 1024


def _probe_backend(timeout: int = 300) -> bool:
    """The axon tunnel can wedge; probe it in a subprocess so a hang can't
    take the bench (and the driver) down with it."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


CPU_FALLBACK_MODEL = dict(
    model_type="llama",
    vocab_size=4096,
    hidden_size=512,
    intermediate_size=1408,
    num_hidden_layers=8,
    num_attention_heads=8,
    num_key_value_heads=4,
    tie_word_embeddings=True,
)


def main() -> int:
    cpu_fallback = not _probe_backend()
    if cpu_fallback:
        # The axon tunnel can be down for reasons outside this repo; a
        # clearly-labeled CPU number beats a hung or absent benchmark.
        print(
            "bench: TPU backend unreachable (probe timed out) — running the "
            "CPU fallback with a tiny model; metric name reflects this",
            file=sys.stderr,
        )
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp

    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models import build_model

    print(f"bench: devices={jax.devices()}", file=sys.stderr)
    model, cfg = build_model(dict(CPU_FALLBACK_MODEL if cpu_fallback else BENCH_MODEL))
    t0 = time.perf_counter()
    params = jax.jit(lambda k: model.init_params(k, jnp.bfloat16))(
        jax.random.PRNGKey(0)
    )
    jax.block_until_ready(params)
    print(f"bench: params initialized in {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    gen = Generator(model, params, max_seq=MAX_SEQ, prefill_chunk=128)
    prompt = list(
        (jax.random.randint(jax.random.PRNGKey(1), (PROMPT_LEN,), 0, cfg.vocab_size))
    )
    prompt = [int(t) for t in prompt]

    # warmup: compiles prefill + decode + sample programs
    t0 = time.perf_counter()
    for i, (tok, _) in enumerate(gen.generate_step(prompt, max_tokens=4)):
        if i == 0:
            print(
                f"bench: warmup TTFT (incl. compiles) {time.perf_counter() - t0:.1f}s",
                file=sys.stderr,
            )
    # measured run
    t0 = time.perf_counter()
    first = None
    n = 0
    for tok, _ in gen.generate_step(prompt, max_tokens=DECODE_TOKENS):
        if first is None:
            first = time.perf_counter()
        n += 1
    end = time.perf_counter()
    ttft = first - t0
    decode_tps = (n - 1) / (end - first)
    prompt_tps = PROMPT_LEN / ttft
    print(
        f"bench: decode={decode_tps:.2f} tok/s prompt={prompt_tps:.1f} tok/s "
        f"TTFT={ttft * 1000:.0f} ms ({n} tokens)",
        file=sys.stderr,
    )
    metric = (
        "decode_tokens_per_sec_tiny_cpu_fallback"
        if cpu_fallback
        else "decode_tokens_per_sec_3b_bf16_1chip"
    )
    # vs_baseline is only meaningful against the documented nominal on the
    # real chip; the CPU fallback reports 0 there.
    vs = 0.0 if cpu_fallback else round(decode_tps / NOMINAL_SINGLE_HOST_MLX_TOKS, 3)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(decode_tps, 2),
                "unit": "tokens/sec",
                "vs_baseline": vs,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
